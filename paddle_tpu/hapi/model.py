"""High-level Model API (fit/evaluate/predict).

Reference parity: python/paddle/hapi/model.py (Model:810 — fit:1299,
evaluate:1515, predict, train_batch:896; StaticGraphAdapter:224 vs
DynamicGraphAdapter:609).

TPU-native: there is only ONE adapter — every train/eval batch runs through a
jit-compiled pure step function (params/buffers/opt-state pytrees in, new
state out).  This is what the reference's StaticGraphAdapter approximated
with Program caching, but with autodiff + XLA fusion over the whole step, and
it subsumes the DynamicGraphAdapter too (the layer's eager state is rebound
to the new device arrays after each step, so dygraph-style inspection still
works between batches).
"""
from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .. import amp as amp_mod
from ..framework import random as _random
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..nn.layer_base import Layer, functional_call, state_pytrees
from ..tensor import Tensor, unwrap
from .engine import (TrainEngine, build_pure_train_step, fetch_floats,
                     host_fetch)


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step_fn = None
        self._eval_fn = None
        self._engine = None
        self.stop_training = False

    # -- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = [m for m in _to_list(metrics)
                         if isinstance(m, Metric)]
        self._train_step_fn = None
        self._eval_fn = None
        self._engine = None
        return self

    # -- compiled steps ----------------------------------------------------
    def _split_params(self):
        params, buffers = state_pytrees(self.network)
        named = dict(self.network.named_parameters())
        trainable = {k: v for k, v in params.items()
                     if not named[k].stop_gradient}
        frozen = {k: v for k, v in params.items() if named[k].stop_gradient}
        return trainable, frozen, buffers

    def _build_train_step(self):
        # the step MATH lives in engine.build_pure_train_step — one body
        # shared with the donated TrainEngine, so the engine's bitwise
        # equivalence to this eager path holds by construction
        return jax.jit(build_pure_train_step(self.network, self._loss,
                                             self._optimizer))

    def _build_eval_step(self):
        network, loss_layer = self.network, self._loss

        @jax.jit
        def step(params, buffers, rng, inputs, labels):
            outs, _ = functional_call(network, params, tuple(inputs), {},
                                      buffers=buffers, rng=rng)
            outs_l = _to_list(outs)
            if loss_layer is not None and labels:
                lv = loss_layer(*(outs_l + list(labels)))
                return outs, jnp.mean(unwrap(lv))
            return outs, jnp.zeros(())

        return step

    def _write_back(self, trainable, buffers):
        named = dict(self.network.named_parameters())
        for k, v in trainable.items():
            named[k]._value = v
        bmap = dict(self.network.named_buffers())
        for k, v in buffers.items():
            bmap[k]._value = v

    # -- batch-level API ---------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        labels = [_as_tensor(x) for x in _to_list(labels)]
        trainable, frozen, buffers = self._split_params()
        opt = self._optimizer
        opt_state = getattr(self, "_opt_state", None)
        if opt_state is None:
            opt_state = opt.init_pytree(trainable)
        opt._step_count += 1
        rng = _random.split_key()
        new_params, new_buffers, new_opt_state, loss_val, outs = \
            self._train_step_fn(
                trainable, frozen, buffers, opt_state,
                jnp.asarray(opt.get_lr(), jnp.float32),
                jnp.asarray(opt._step_count, jnp.int32), rng,
                inputs, labels)
        self._write_back(new_params, new_buffers)
        self._opt_state = new_opt_state
        metrics_out = [float(loss_val)]
        for m in self._metrics:
            m.update(unwrap(m.compute(*( _to_list(outs) + labels))))
        return metrics_out if len(metrics_out) > 1 else metrics_out[0]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        if self._eval_fn is None:
            self._eval_fn = self._build_eval_step()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        labels = [_as_tensor(x) for x in _to_list(labels)]
        params, buffers = state_pytrees(self.network)
        rng = _random.split_key()
        outs, loss_val = self._eval_fn(params, buffers, rng, inputs, labels)
        return outs, float(loss_val)

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        outs, _ = self.eval_batch_no_loss(inputs)
        return outs

    def eval_batch_no_loss(self, inputs):
        if self._eval_fn is None:
            self._eval_fn = self._build_eval_step()
        params, buffers = state_pytrees(self.network)
        rng = _random.split_key()
        outs, lv = self._eval_fn(params, buffers, rng, inputs, [])
        return outs, lv

    # -- fault tolerance ---------------------------------------------------
    def _ft_state(self, it_count):
        """Checkpointable training state: trainable params + buffers +
        optimizer slots + loop counters, as one pytree of arrays.  When
        the device-resident engine is live its state is authoritative
        (the Layer tree is only synced at epoch boundaries) and must be
        MATERIALIZED to host — the engine donates those buffers on the
        next dispatch, which would race orbax's async save."""
        eng = self._engine
        if eng is not None and eng.active:
            return eng.ft_state(it_count)
        trainable, _frozen, buffers = self._split_params()
        opt_state = getattr(self, "_opt_state", None)
        if opt_state is None:
            opt_state = self._optimizer.init_pytree(trainable)
        return {"params": trainable, "buffers": buffers, "opt": opt_state,
                "meta": {"it": jnp.int32(it_count),
                         "opt_steps": jnp.int32(
                             self._optimizer._step_count)}}

    def _ft_restore(self, mgr):
        """Auto-resume: load the latest checkpoint (if any) back into the
        live network/optimizer; returns the iteration to fast-forward to."""
        step0, back = mgr.restore_latest(template=self._ft_state(0))
        if step0 is None:
            return 0
        self._write_back(back["params"], back["buffers"])
        self._opt_state = back["opt"]
        self._optimizer._step_count = int(back["meta"]["opt_steps"])
        restart = os.environ.get("PADDLE_RESTART_COUNT", "0")
        print(f"fit: resumed from checkpoint at iteration {step0} "
              f"(restart #{restart})", flush=True)
        return int(back["meta"]["it"])

    # -- loop-level API ----------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, fault_tolerant=False,
            resume=None, checkpoint_interval=None, mesh=None,
            sharding_rule=None):
        """[fault tolerance — opt-in] `resume=<dir>` (or `resume=True`
        with `save_dir`) auto-resumes from the newest checkpoint in that
        directory and checkpoints every `checkpoint_interval` iterations
        (default: each epoch end).  `fault_tolerant=True` additionally
        latches SIGTERM/SIGINT, finishes the in-flight batch, writes an
        emergency checkpoint, and exits with
        `distributed.PREEMPTED_EXIT_CODE` so a launcher started with
        `--max_restarts` relaunches and resumes — see
        distributed/resilience.py.  Resume is bitwise-exact when data
        order and seeding are deterministic (`shuffle=False` +
        `paddle.seed`).

        [SPMD scaling — opt-in] `mesh=` a `jax.sharding.Mesh`, a shape
        dict like `{"dp": 8}`, or nothing: an ambient
        `distributed.mesh_guard` (or `FLAGS_mesh_shape`) is picked up
        automatically.  The engine then compiles ONE global step with
        NamedSharding in/out shardings: params/opt-state replicated over
        `dp` (per-param placement via `sharding_rule(name, param) ->
        PartitionSpec` or `distributed.annotate` for an `mp` axis), the
        global batch split over `dp`, XLA inserting the collectives
        (GSPMD) — so `batch_size` is the GLOBAL batch and throughput
        scales with the dp degree.  All single-chip fit contracts
        (donation, sync-free stepping, compile cache, checkpoints,
        callbacks) are preserved; see README "Scaling"."""
        from .callbacks import config_callbacks

        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        self._save_dir = save_dir
        self.stop_training = False
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir,
            metrics=[m._name for m in self._metrics])
        from .callbacks import LRScheduler as _LRCb
        from .callbacks import ModelCheckpoint as _CkptCb
        from .callbacks import ProgBarLogger as _PBCb

        # metric.accumulate() is host-side work — only compute per-batch
        # when a log step fires or a user callback might consume it
        user_cbs = any(not isinstance(c, (_PBCb, _LRCb, _CkptCb))
                       for c in cbks)
        ft_mgr = None
        start_it = 0
        guard = None
        if fault_tolerant or resume:  # resume=False/None/"" ⇒ off
            from ..distributed import resilience as _res
            from ..distributed.checkpoint import CheckpointManager
            from ..utils import chaos as _chaos

            ckpt_dir = resume if isinstance(resume, str) else save_dir
            if not ckpt_dir:
                raise ValueError("fault_tolerant/resume needs a checkpoint "
                                 "directory: pass resume=<dir> or save_dir=")
            ckpt_dir = os.path.join(ckpt_dir, "resilient")
            ft_mgr = CheckpointManager(ckpt_dir, max_to_keep=2)
            try:
                start_it = self._ft_restore(ft_mgr)
                if fault_tolerant:
                    guard = _res.PreemptionGuard()
                    guard.__enter__()
            except BaseException:
                ft_mgr.close()
                raise

        # Device-resident engine (hapi/engine.py): ONE state snapshot per
        # fit, donated buffers, no per-step host sync.  When user
        # callbacks or metrics need fresh per-batch values the loop
        # drains the loss ring every step (same observable behavior as
        # the old train_batch loop); otherwise losses are fetched in one
        # batch at log_freq boundaries and epoch ends.
        from ..utils.profiler import StepTimers

        if self._engine is None:
            self._engine = TrainEngine(self)
        engine = self._engine
        engine.begin(mesh=mesh, sharding_rule=sharding_rule)
        prev_placement = None
        if engine.mesh is not None:
            # the prefetch thread device-puts each global batch straight
            # to its dp sharding, overlapping host→device transfer of
            # batch N+1 with device compute of batch N
            from functools import partial as _partial

            from ..framework.transfer import shard_batch
            prev_placement = loader.placement
            loader.placement = _partial(shard_batch, mesh=engine.mesh)
        eager_sync = user_cbs or bool(self._metrics)
        timers = StepTimers()
        self._last_fit_timers = timers
        _END = object()

        history = {"loss": []}
        it_count = 0
        try:
            cbks.on_train_begin({})
            for epoch in range(epochs):
                self.network.train()
                for m in self._metrics:
                    m.reset()
                cbks.on_epoch_begin(epoch, {})
                # fold user writes to Layer params/buffers (epoch-end
                # callbacks: SWA/EMA write-back, re-init, pruning) back
                # into the device-resident state
                engine.refresh_from_layers()
                losses = []
                data_iter = iter(loader)
                step_i = -1
                while True:
                    with timers.scope("data"):
                        batch = next(data_iter, _END)
                    if batch is _END:
                        break
                    step_i += 1
                    if it_count < start_it:
                        # fast-forward over already-trained batches,
                        # consuming one rng key each to keep the stream
                        # aligned with the uninterrupted run.  A SIGTERM
                        # here exits immediately — nothing new to save,
                        # the restored checkpoint is still the newest
                        if guard is not None and guard.preempted:
                            raise SystemExit(_res.PREEMPTED_EXIT_CODE)
                        _random.split_key()
                        it_count += 1
                        continue
                    cbks.on_train_batch_begin(step_i, {})
                    if ft_mgr is not None:
                        # fault-injection hook (crash/preempt/slow) so the
                        # fit() recovery paths are chaos-testable too
                        _chaos.on_step(it_count + 1)
                    batch = _to_list(batch)
                    inputs, labels = self._split_batch(batch)
                    inputs = [_as_tensor(x) for x in inputs]
                    labels = [_as_tensor(x) for x in labels]
                    if user_cbs:
                        # per-batch weight mutations (WGAN-style clipping
                        # callbacks) only possible with user callbacks —
                        # identity-scan for them before dispatching
                        engine.refresh_from_layers()
                    with timers.scope("dispatch"):
                        outs = engine.step(inputs, labels)
                    it_count += 1
                    log_step = bool(log_freq) and step_i % log_freq == 0
                    if eager_sync or log_step:
                        with timers.scope("sync"):
                            losses.extend(engine.drain())
                    if user_cbs:
                        # full eager semantics for custom callbacks: they
                        # see CURRENT weights in on_train_batch_end (the
                        # old loop wrote back every batch; vanilla runs
                        # keep the async no-copy path).  Opt slots sync
                        # only at boundaries — callbacks observe weights
                        engine.write_back(copy=True, sync_opt=False)
                    if self._metrics:
                        with host_fetch():
                            for m in self._metrics:
                                m.update(unwrap(m.compute(
                                    *(_to_list(outs) + labels))))
                    logs = {"loss": losses[-1] if losses else float("nan"),
                            "batch_size": batch_size}
                    if user_cbs or log_step:
                        for m in self._metrics:
                            logs[m._name] = np.mean(
                                _to_list(m.accumulate()))
                    cbks.on_train_batch_end(step_i, logs)
                    if ft_mgr is not None:
                        if (checkpoint_interval
                                and it_count % checkpoint_interval == 0):
                            ft_mgr.save(it_count, self._ft_state(it_count))
                        if guard is not None and guard.preempted:
                            # in-flight batch done: emergency checkpoint,
                            # then the distinct "preempted" exit so the
                            # launcher restarts us
                            ft_mgr.save(it_count, self._ft_state(it_count),
                                        force=True)
                            ft_mgr.wait()
                            raise SystemExit(_res.PREEMPTED_EXIT_CODE)
                    if num_iters is not None and it_count >= num_iters:
                        break
                with timers.scope("sync"):
                    losses.extend(engine.drain())
                # epoch-boundary write-back: the Layer tree gets device
                # COPIES so checkpoints/eval/user inspection see current
                # values while the engine keeps donating its own buffers
                engine.write_back(copy=True)
                if ft_mgr is not None and not checkpoint_interval \
                        and it_count > start_it:
                    ft_mgr.save(it_count, self._ft_state(it_count),
                                force=True)
                # losses can be empty when resume fast-forwarded the epoch
                history["loss"].append(
                    float(np.mean(losses)) if losses else float("nan"))
                epoch_logs = {"loss": history["loss"][-1]}
                for m in self._metrics:
                    epoch_logs[m._name] = np.mean(_to_list(m.accumulate()))
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    cbks.on_eval_begin({})
                    eval_res = self.evaluate(eval_data,
                                             batch_size=batch_size,
                                             verbose=0)
                    history.setdefault("eval_loss", []).append(
                        eval_res.get("loss"))
                    epoch_logs.update({f"eval_{k}": v
                                       for k, v in eval_res.items()})
                    cbks.on_eval_end(eval_res)
                cbks.on_epoch_end(epoch, epoch_logs)
                # SIGTERM during epoch-end eval/callbacks must still turn
                # into a clean preempted exit (not a SIGKILL after the
                # grace window); a final-epoch latch just finishes the run
                if guard is not None and guard.preempted \
                        and epoch + 1 < epochs:
                    if it_count > start_it:
                        ft_mgr.save(it_count, self._ft_state(it_count),
                                    force=True)
                        ft_mgr.wait()
                    raise SystemExit(_res.PREEMPTED_EXIT_CODE)
                if self.stop_training:
                    break
                if num_iters is not None and it_count >= num_iters:
                    break
        finally:
            # final write-back: the engine's device-resident state becomes
            # the Layer tree's state again (single source of truth for
            # train_batch/save/parameters after fit returns) — even when
            # fit is unwinding on an exception/preemption
            import sys as _sys
            if _sys.exc_info()[0] is None:
                # success path: a failed final write-back means the Layer
                # tree holds stale weights — that must surface, not pass
                engine.finish()
            else:
                try:
                    engine.finish()
                except Exception:  # noqa: BLE001 - don't mask the real error
                    pass
            if engine.mesh is not None:
                loader.placement = prev_placement
            # a crash mid-fit must still flush/close callback resources
            cbks.on_train_end({})
            if guard is not None:
                guard.__exit__(None, None, None)
            if ft_mgr is not None:
                ft_mgr.wait()
                ft_mgr.close()
        return history

    def _split_batch(self, batch):
        n_label = len(_to_list(self._labels)) or 1
        if len(batch) == 1:
            return batch, []
        return batch[:-n_label], batch[-n_label:]

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, shuffle=False,
                       num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        # hoisted once per evaluate (the old loop re-split the Layer tree
        # and synced float(loss) on every batch); losses stay on device
        # and are fetched in one batched transfer at the end
        self.network.eval()
        if self._eval_fn is None:
            self._eval_fn = self._build_eval_step()
        params, buffers = state_pytrees(self.network)
        losses_dev = []
        for batch in loader:
            batch = _to_list(batch)
            inputs, labels = self._split_batch(batch)
            inputs = [_as_tensor(x) for x in inputs]
            labels = [_as_tensor(x) for x in labels]
            rng = _random.split_key()
            outs, loss = self._eval_fn(params, buffers, rng, inputs, labels)
            losses_dev.append(loss)
            if self._metrics:
                with host_fetch():
                    for m in self._metrics:
                        m.update(unwrap(m.compute(*(_to_list(outs) +
                                                    labels))))
        losses = fetch_floats(losses_dev)
        res = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            res[m._name] = m.accumulate()
        if verbose:
            print("Eval:", res, flush=True)
        return res

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, shuffle=False,
                       num_workers=num_workers)
        outputs = []
        for batch in loader:
            batch = _to_list(batch)
            inputs, _ = self._split_batch(batch)
            outs, _ = self.eval_batch_no_loss([_as_tensor(x) for x in inputs])
            outputs.append(outs)
        if stack_outputs and outputs:
            from .. import tensor_ops as T

            if isinstance(outputs[0], Tensor):
                return [T.concat(outputs, axis=0)]
        return outputs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io_state import save as fsave

        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            opt_state = getattr(self, "_opt_state", None)
            payload = {"step_count": self._optimizer._step_count}
            if opt_state is not None:
                payload["opt_state"] = jax.tree_util.tree_map(np.asarray,
                                                              opt_state)
            fsave(payload, path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io_state import load as fload

        self.network.set_state_dict(fload(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and os.path.exists(opt_path):
            payload = fload(opt_path)
            if self._optimizer is not None:
                self._optimizer._step_count = payload.get("step_count", 0)
            if "opt_state" in payload:
                self._opt_state = jax.tree_util.tree_map(
                    jnp.asarray, payload["opt_state"])
        return self

    def serve(self, host="127.0.0.1", port=8866, *, input_spec=None,
              max_batch_size=None, batch_timeout_ms=None, buckets=None,
              queue_depth=None, blocking=True,
              install_signal_handlers=True):
        """Serve this model over HTTP with adaptive batching
        (paddle_tpu.serving): concurrent /predict requests are coalesced
        into padded shape-bucket batches, every bucket is AOT-warmed
        before the port opens, and SIGTERM drains gracefully.

        `input_spec` (or the Model's constructor `inputs`) provides the
        per-input (shape, dtype) used for warmup — dims of -1/None are
        serving-variable (batch, and sequence when `buckets` carries a
        seq grid).  With `blocking=False` returns the started
        `ServingServer` (use `.url`, `.shutdown()`); otherwise blocks
        until SIGTERM and returns the drain exit code (0 = clean).
        """
        from ..serving import ServingEngine, ServingServer

        self.network.eval()
        spec = input_spec if input_spec is not None else self._inputs
        engine = ServingEngine(
            self.network, max_batch_size=max_batch_size,
            batch_timeout_ms=batch_timeout_ms, buckets=buckets,
            queue_depth=queue_depth,
            input_specs=_to_list(spec) if spec is not None else None)
        server = ServingServer(
            engine, host=host, port=port,
            install_signal_handlers=install_signal_handlers).start()
        if blocking:
            print(f"serving on {server.url} (SIGTERM drains gracefully)",
                  flush=True)
            return server.wait()
        return server

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        """Parameter summary (hapi Model.summary)."""
        return summary(self.network, input_size, dtype)


def summary(net, input_size=None, dtypes=None):
    lines = []
    total = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape))
        total += n
        lines.append(f"{name:60s} {str(p.shape):20s} {n}")
    out = "\n".join(lines) + f"\nTotal params: {total}"
    print(out)
    return {"total_params": total}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Forward FLOPs of a network (hapi/dynamic_flops.py).  TPU-native:
    XLA's own cost model counts them — jit-compile the forward on zero
    inputs of `input_size` and read compiled cost_analysis, which covers
    every op the hardware will actually run (the reference hand-counts a
    per-layer table)."""
    import jax
    import jax.numpy as jnp

    from ..nn.layer_base import functional_call, state_pytrees
    from ..tensor import Tensor

    sizes = input_size if isinstance(input_size[0], (list, tuple)) \
        else [input_size]
    # preserve PER-SUBLAYER modes (a blanket net.train() would flip
    # deliberately-frozen sublayers back to training)
    modes = [(l, l.training) for l in net.sublayers(include_self=True)] \
        if hasattr(net, "sublayers") else [(net, net.training)]
    net.eval()
    try:
        params, buffers = state_pytrees(net)

        def fwd(params, *xs):
            out, _ = functional_call(net, params,
                                     tuple(Tensor(x) for x in xs),
                                     buffers=buffers)
            outs = out if isinstance(out, (tuple, list)) else [out]
            return tuple(o.value for o in outs)

        xs = [jnp.zeros(tuple(s), jnp.float32) for s in sizes]
        compiled = jax.jit(fwd).lower(params, *xs).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
        if "flops" not in ca:
            import warnings

            warnings.warn(
                "flops(): this backend's compiled cost_analysis() does "
                "not report a 'flops' key; returning 0", stacklevel=2)
        total = int(ca.get("flops", 0.0))
        if print_detail:
            print(f"XLA-analyzed forward FLOPs for input {input_size}: "
                  f"{total:,}")
        return total
    finally:
        for layer, mode in modes:
            layer.training = mode
