"""hapi.model_summary — the module paddle.summary lives in upstream
(reference python/paddle/hapi/model_summary.py); re-exported from the
XLA-cost-analysis-backed implementation in hapi/model.py."""
from .model import summary  # noqa: F401

__all__ = ["summary"]
