"""`paddle.incubate` — experimental-API namespace.

Reference parity: python/paddle/incubate/__init__.py — exports the
incubating `optimizer` module (LookAhead, ModelAverage) and the
`reader` tooling.  Here those graduated implementations live in
paddle_tpu.optimizer.wrappers / paddle_tpu.reader; this namespace
re-exports them under the incubate paths fluid-era scripts use.
"""
from .. import reader  # noqa: F401
from . import optimizer  # noqa: F401

__all__ = ["reader", "optimizer"]
