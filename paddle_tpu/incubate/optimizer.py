"""incubate.optimizer — LookAhead / ModelAverage incubating paths.

Reference parity: python/paddle/incubate/optimizer/__init__.py (these
graduated into paddle_tpu.optimizer.wrappers; re-exported here under
the incubate names).
"""
from ..optimizer.wrappers import (  # noqa: F401
    EMA, ExponentialMovingAverage, LookaheadOptimizer, ModelAverage)

LookAhead = LookaheadOptimizer  # incubate spelling (incubate/optimizer/lookahead.py)

__all__ = ["LookAhead", "ModelAverage"]
