"""paddle.inference — the serving path.

Reference parity: paddle/fluid/inference/ (SURVEY.md §2.6):
  * AnalysisConfig        → Config (api/analysis_config.cc knob surface;
                            CUDA/MKLDNN/TensorRT knobs accepted and inert)
  * AnalysisPredictor     → Predictor (api/analysis_predictor.cc:306 Run /
                            ZeroCopyRun) — named input/output handles
  * save/load_inference_model (fluid io.py:1198/1411) — export artifact
TPU-native: the "optimized program" is an AOT-compiled function.  Export
serializes the jitted forward as StableHLO via jax.export (.pdexport) plus
weights (.pdiparams) and an input-spec manifest (.pdmodel.json); the
predictor deserializes and calls it — no Python model code needed at serve
time (the AnalysisPredictor contract).  A pickle fallback (.pdmodel) keeps
models with python-side control flow loadable.
"""
from __future__ import annotations

import json
import logging
import os
import pickle

import numpy as np

import jax
import jax.numpy as jnp

try:  # newer jax exposes jax.export lazily; older needs the submodule import
    import jax.export  # noqa: F401
except ImportError:  # pragma: no cover - very old jax
    pass

from ..framework.dtype import convert_dtype
from ..tensor import Tensor

logger = logging.getLogger("paddle_tpu.inference")

__all__ = ["Config", "Predictor", "create_predictor",
           "save_inference_model", "load_inference_model", "PrecisionType",
           "DataType", "PlaceType", "aot_compile", "spec_tree"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class DataType:
    """Tensor element types over the serving boundary
    (paddle_infer_declare.h PaddleDType)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType:
    """Handle placement (paddle_tensor.h PlaceType); TPU serves from the
    accelerator, kCPU is the host fallback."""
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kTPU = 2
    kXPU = 3


def _natural_key(name):
    """Sort key splitting digit runs so x2 < x10 (AnalysisPredictor binds
    feeds by declaration order; numeric-suffix names must follow it)."""
    import re
    return [int(p) if p.isdigit() else p
            for p in re.split(r"(\d+)", str(name))]


# Inert-knob warnings fire ONCE per process per knob (serving loops call
# these from config templates; per-call spam would drown real logs).
_warned_inert: set[str] = set()


def _warn_inert(knob: str, detail: str):
    if knob not in _warned_inert:
        _warned_inert.add(knob)
        logger.warning(
            "inference.Config.%s is accepted but INERT on this backend — "
            "%s (XLA is the engine; see MIGRATION.md §4)", knob, detail)


class Config:
    """AnalysisConfig parity (api/analysis_config.cc)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        # paddle 2.x: Config(path_prefix) or Config(model_file, params_file)
        if model_dir is not None and prog_file is None:
            self._path_prefix = str(model_dir)
        elif prog_file is not None:
            self._path_prefix = os.path.splitext(str(model_dir))[0]
        else:
            self._path_prefix = None
        self._use_tpu = True
        self._precision = PrecisionType.Float32
        self._switches = {}

    def set_model(self, model_dir, params_file=None):
        self._path_prefix = os.path.splitext(str(model_dir))[0]

    def model_dir(self):
        return self._path_prefix

    # device knobs — TPU is the target; CUDA knobs accepted, inert (and
    # say so once, so serving users aren't misled into thinking a GPU /
    # TensorRT / MKLDNN path is active)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        _warn_inert("enable_use_gpu", "no CUDA path exists; the model "
                    "serves from the TPU/CPU XLA backend")
        self._switches["use_gpu"] = True

    def disable_gpu(self):
        self._switches["use_gpu"] = False

    def enable_xpu(self, *a, **k):
        _warn_inert("enable_xpu", "no XPU path exists")
        self._switches["use_xpu"] = True

    def enable_tpu(self):
        self._use_tpu = True
        self._switches["use_tpu"] = True

    def use_tpu(self) -> bool:
        return self._use_tpu

    def set_cpu_math_library_num_threads(self, n):
        self._switches["cpu_threads"] = n

    def enable_mkldnn(self):
        _warn_inert("enable_mkldnn", "MKLDNN is a documented non-goal")
        self._switches["mkldnn"] = True

    def enable_tensorrt_engine(self, *a, **k):
        _warn_inert("enable_tensorrt_engine",
                    "TensorRT is a documented non-goal")
        self._switches["tensorrt"] = True  # inert: XLA is the engine

    def enable_memory_optim(self):
        self._switches["memory_optim"] = True

    def switch_ir_optim(self, x=True):
        self._switches["ir_optim"] = x

    def switch_use_feed_fetch_ops(self, x=False):
        self._switches["feed_fetch_ops"] = x

    def switch_specify_input_names(self, x=True):
        self._switches["specify_input_names"] = x

    def set_precision(self, p):
        self._precision = p

    def summary(self):
        return json.dumps({"path": self._path_prefix,
                           "switches": self._switches}, indent=2)


_MISSING = object()  # bucket-cache sentinel: None = "compile failed, use
                     # per-call dispatch" is itself a cached outcome


class _Handle:
    """ZeroCopy input/output handle (api/details/zero_copy_tensor.cc)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass  # shapes come from the bound array

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def share_external_data(self, arr):
        self._value = arr


class Predictor:
    """AnalysisPredictor parity: named handles + Run loop.

    Serving addition: a bucket-aware callable cache — every distinct
    input-shape signature ("bucket") is AOT-lowered and compiled ONCE
    (`warm()` does it ahead of traffic), and subsequent `run` calls on
    that bucket go straight to the compiled executable with zero
    retracing/recompilation.  `compile_count` exposes the number of
    bucket compiles so serving tests can tripwire recompile storms.
    """

    def __init__(self, config: Config):
        if isinstance(config, str):
            config = Config(config)
        self.config = config
        self._bucket_cache = {}
        self.compile_count = 0
        prefix = config.model_dir()
        if prefix is None:
            raise ValueError("Config has no model path")
        self._load(prefix)

    @classmethod
    def from_layer(cls, layer):
        """Serve an in-memory Layer through the same Predictor surface
        (bucket cache included) without an export round-trip."""
        self = cls.__new__(cls)
        self.config = None
        self._bucket_cache = {}
        self.compile_count = 0
        self._input_specs = None
        self._init_from_layer(layer)
        return self

    # -- loading ----------------------------------------------------------
    def _load(self, prefix):
        manifest_path = prefix + ".pdmodel.json"
        export_path = prefix + ".pdexport"
        if os.path.exists(manifest_path) and os.path.exists(export_path):
            with open(manifest_path) as f:
                manifest = json.load(f)
            with open(export_path, "rb") as f:
                self._exported = jax.export.deserialize(f.read())
            self._input_names = manifest["input_names"]
            self._output_names = manifest["output_names"]
            self._input_specs = manifest.get("input_specs")
            params = {}
            aot_params = prefix + ".pdaotparams"
            with open(aot_params if os.path.exists(aot_params)
                      else prefix + ".pdiparams", "rb") as f:
                raw = pickle.load(f)
            for k, v in raw.items():
                params[k] = jnp.asarray(v)
            self._params = params
            self._mode = "aot"
            return
        # fallback: pickled Layer artifact (paddle_tpu.jit.save format)
        from .. import jit as _jit
        self._input_specs = None
        self._init_from_layer(_jit.load(prefix))

    def _init_from_layer(self, layer):
        layer.eval()
        from ..nn.layer_base import functional_call, state_pytrees
        params, buffers = state_pytrees(layer)

        def fwd(params, *args):
            out, _ = functional_call(layer, params,
                                     tuple(Tensor(a) for a in args),
                                     buffers=buffers)
            if isinstance(out, (tuple, list)):
                return tuple(o.value for o in out)
            return (out.value,)

        self._params = params
        self._jitted = jax.jit(fwd)
        self._input_names = None  # discovered at first run
        self._output_names = None
        self._mode = "jit"

    # -- bucket-aware callable cache --------------------------------------
    @staticmethod
    def _bucket_key(arrays):
        return tuple((tuple(int(d) for d in a.shape),
                      str(np.dtype(a.dtype))) for a in arrays)

    def _get_bucket(self, arrays):
        """Compiled executable for this exact input signature (compiling
        it on first sight), or None when AOT lowering is unavailable for
        it — callers then take the legacy dispatch path."""
        key = self._bucket_key(arrays)
        fn = self._bucket_cache.get(key, _MISSING)
        if fn is not _MISSING:
            return fn
        try:
            specs = [jax.ShapeDtypeStruct(shape, np.dtype(dt))
                     for shape, dt in key]
            if self._mode == "aot":
                exported = self._exported

                def call(params, *xs):
                    return exported.call(*jax.tree.leaves(params), *xs)

                fn = jax.jit(call).lower(self._params, *specs).compile()
            else:
                fn = self._jitted.lower(self._params, *specs).compile()
            self.compile_count += 1
        except Exception as e:  # noqa: BLE001 - bucket cache is an optimization
            logger.debug("bucket compile failed for %s (%s: %s) — using "
                         "per-call dispatch", key, type(e).__name__, e)
            fn = None
        self._bucket_cache[key] = fn
        return fn

    def warm(self, shapes, dtypes=None):
        """AOT-compile the bucket for `shapes` (one shape tuple per
        input, batch dim included) ahead of traffic.  Returns True when
        the bucket is servable without further compilation."""
        if dtypes is None:
            dtypes = [s["dtype"] for s in (self._input_specs or [])] \
                or ["float32"] * len(shapes)
        arrays = [np.zeros(tuple(shape), np.dtype(dt))
                  for shape, dt in zip(shapes, dtypes)]
        fn = self._get_bucket(arrays)
        if fn is not None and self._mode == "jit" \
                and self._input_names is None:
            self.run(arrays)  # discover input/output names once
        return fn is not None

    # -- handle API (reference get_input_handle/get_output_handle) --------
    def get_input_names(self):
        return list(self._input_names or [])

    def get_output_names(self):
        return list(self._output_names or [])

    def get_input_handle(self, name):
        if not hasattr(self, "_in_handles"):
            self._in_handles = {}
        return self._in_handles.setdefault(name, _Handle(name))

    def get_output_handle(self, name):
        if not hasattr(self, "_out_handles"):
            self._out_handles = {}
        return self._out_handles.setdefault(name, _Handle(name))

    def run(self, inputs=None):
        """Run with positional numpy inputs (returns list of numpy), or
        with bound handles when inputs is None (ZeroCopyRun path).

        Dispatch goes through the bucket cache: the first call on a new
        input signature AOT-compiles it, every later call reuses the
        compiled executable (zero retrace/recompile — the property the
        serving engine's warmup relies on)."""
        if inputs is None:
            # Natural-sort fallback: lexicographic sorted() would bind x10
            # before x2 for models with 11+ inputs (advisor r1/r2 finding).
            names = self._input_names or sorted(
                getattr(self, "_in_handles", {}), key=_natural_key)
            inputs = [self._in_handles[n]._value for n in names]
        arrays = [np.asarray(x.numpy() if isinstance(x, Tensor) else x)
                  for x in inputs]
        fn = self._get_bucket(arrays)
        if fn is not None:
            outs = fn(self._params, *arrays)
        elif self._mode == "aot":
            outs = self._exported.call(*jax.tree.leaves(self._params),
                                       *(jnp.asarray(a) for a in arrays))
        else:
            outs = self._jitted(self._params, *arrays)
        if self._input_names is None:
            self._input_names = [f"x{i}" for i in range(len(arrays))]
            self._output_names = [f"out{i}" for i in range(
                len(outs) if isinstance(outs, (tuple, list)) else 1)]
        outs = [np.asarray(o) for o in (outs if isinstance(outs, (tuple, list))
                                        else [outs])]
        for i, n in enumerate(self._output_names or []):
            if hasattr(self, "_out_handles") and n in self._out_handles:
                self._out_handles[n]._value = outs[i]
        return outs


def create_predictor(config):
    return Predictor(config)


def spec_tree(tree):
    """ShapeDtypeStructs mirroring an argument pytree — the AOT lowering
    input for ``aot_compile``.  Scalars should already be committed
    numpy scalars (np.int32/np.float32): a weak-typed python int would
    lower a different program than the one traffic calls."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.result_type(a)),
        tree)


def aot_compile(fn, arg_specs, *, donate_argnums=(), out_shardings=None):
    """Lower + compile ``fn`` for one EXACT argument signature, ahead of
    traffic (the Predictor bucket-cache discipline, factored out for
    engines that manage their own executables — the generation engine's
    donated decode step).  ``arg_specs`` are ShapeDtypeStructs (or
    pytrees of them, e.g. from ``spec_tree``); ``donate_argnums`` is
    forwarded to jax.jit, so a donated state argument keeps its
    buffer-reuse contract in the compiled executable.

    Calling the result with a mismatched shape/dtype raises instead of
    recompiling — steady-state serving performs zero XLA compiles, and a
    signature drift is a loud error rather than a silent compile storm.

    ``out_shardings`` (optional, a pytree of NamedShardings matching the
    outputs) pins result placements — the layout-aware generation engine
    passes its state shardings so a donated, tp-sharded decode state
    comes back exactly where it went in (donation requires in == out).
    """
    if out_shardings is None:
        jitted = jax.jit(fn, donate_argnums=donate_argnums)
    else:
        jitted = jax.jit(fn, donate_argnums=donate_argnums,
                         out_shardings=out_shardings)
    return jitted.lower(*arg_specs).compile()




def symbolic_input_specs(manifest_shapes, dtypes):
    """ShapeDtypeStructs for export: dims marked -1 become symbolic
    (jax.export) so the served artifact accepts any size there; returns
    None when every dim is concrete."""
    if not any(d < 0 for shp in manifest_shapes for d in shp):
        return None
    scope = jax.export.SymbolicScope()
    specs = []
    for i, (shp, dt) in enumerate(zip(manifest_shapes, dtypes)):
        dims = ",".join(f"d{i}_{j}" if d < 0 else str(d)
                        for j, d in enumerate(shp))
        shape = jax.export.symbolic_shape(dims, scope=scope)
        specs.append(jax.ShapeDtypeStruct(shape, np.dtype(dt)))
    return specs


def write_export_artifacts(path_prefix, exported, input_names,
                           manifest_shapes, dtypes, aot_params=None):
    """Serialize a jax.export.Exported + manifest (+ AOT param payload)
    in the layout Predictor._load reads — the ONE writer both
    inference.save_inference_model and static.save_inference_model use."""
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdexport", "wb") as f:
        f.write(exported.serialize())
    if aot_params is not None:
        with open(path_prefix + ".pdaotparams", "wb") as f:
            pickle.dump(aot_params, f)
    manifest = {
        "input_names": list(input_names),
        "output_names": [f"out{i}"
                         for i in range(len(exported.out_avals))],
        "input_specs": [{"shape": list(shp), "dtype": str(np.dtype(dt))}
                        for shp, dt in zip(manifest_shapes, dtypes)],
        "format": "jax.export/stablehlo",
    }
    with open(path_prefix + ".pdmodel.json", "w") as f:
        json.dump(manifest, f, indent=2)
    return path_prefix


def save_inference_model(path_prefix, layer_or_feed, fetch_vars=None,
                         input_spec=None, example_inputs=None):
    """Export a Layer for serving.

    TPU form: save_inference_model(prefix, layer, example_inputs=[...])
    — AOT-serializes the jitted forward (StableHLO) + weights + manifest.
    The fluid (executor, feed_names, fetch_targets) signature is accepted
    via paddle_tpu.distributed.fleet.save_inference_model.
    Reference: fluid io.py save_inference_model:1198.
    """
    from ..nn.layer_base import Layer, functional_call, state_pytrees

    layer = layer_or_feed
    if not isinstance(layer, Layer):
        raise TypeError("save_inference_model expects a Layer; for the "
                        "fluid executor signature use fleet.save_inference_model")
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    was_training = layer.training
    layer.eval()
    try:
        params, buffers = state_pytrees(layer)

        # Dynamic dims (-1/None) in an InputSpec export symbolically via
        # jax.export so the served artifact accepts ANY size there. Baking
        # -1 to a concrete 1 (the old behavior) silently served batch-1
        # only (advisor r1/r2 finding).
        sym_in_specs = None
        manifest_shapes = None
        if input_spec is not None and example_inputs is not None:
            if len(input_spec) != len(example_inputs):
                raise ValueError(
                    f"input_spec has {len(input_spec)} entries but "
                    f"example_inputs has {len(example_inputs)}")
            for i, (s, a) in enumerate(zip(input_spec, example_inputs)):
                ashape = tuple(np.shape(np.asarray(
                    a.numpy() if isinstance(a, Tensor) else a)))
                if len(s.shape) != len(ashape) or any(
                        d is not None and d >= 0 and d != ad
                        for d, ad in zip(s.shape, ashape)):
                    raise ValueError(
                        f"input_spec[{i}] shape {list(s.shape)} does not "
                        f"match example_inputs[{i}] shape {list(ashape)}")
        if input_spec is not None:
            manifest_shapes = [[-1 if (d is None or d < 0) else int(d)
                                for d in s.shape] for s in input_spec]
            sym_in_specs = symbolic_input_specs(
                manifest_shapes,
                [convert_dtype(s.dtype) for s in input_spec])
        if example_inputs is None and input_spec is not None:
            example_inputs = [
                np.zeros([d if d and d > 0 else 1 for d in s.shape],
                         convert_dtype(s.dtype)) for s in input_spec]
        from .. import jit as _jit
        _jit.save(layer, path_prefix)  # .pdmodel + .pdiparams (full state)
        # AOT arg payload: PARAMS ONLY — buffers are baked into the
        # exported graph as constants, so the .call() arg structure must
        # match exactly (a buffer-carrying model, e.g. BN or QAT scales,
        # would otherwise mismatch the exported pytree)
        with open(path_prefix + ".pdaotparams", "wb") as f:
            pickle.dump({k: np.asarray(v) for k, v in params.items()}, f)

        if example_inputs is None:
            return path_prefix

        # export compiles the forward, so data-dependent python control
        # flow must be AST-converted here exactly as @to_static would
        # (otherwise an eager-trained model with `if tensor:` branches
        # fails at trace time); no-op when nothing converts
        import types

        from ..jit import _maybe_convert

        cls_fwd = type(layer).forward
        conv_fwd = _maybe_convert(cls_fwd)
        if conv_fwd is not cls_fwd and "forward" not in layer.__dict__:
            layer.forward = types.MethodType(conv_fwd, layer)
            converted_patch = True
        else:
            converted_patch = False

        def fwd(*flat):
            n_par = len(jax.tree.leaves(params))
            par = jax.tree.unflatten(jax.tree.structure(params),
                                     flat[:n_par])
            args = flat[n_par:]
            out, _ = functional_call(layer, par,
                                     tuple(Tensor(a) for a in args),
                                     buffers=buffers)
            if isinstance(out, (tuple, list)):
                return tuple(o.value for o in out)
            return (out.value,)

        arrays = [jnp.asarray(np.asarray(
            x.numpy() if isinstance(x, Tensor) else x))
            for x in example_inputs]
        in_specs = sym_in_specs if sym_in_specs is not None else [
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays]
        specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in jax.tree.leaves(params)] + list(in_specs)
        try:
            exported = jax.export.export(jax.jit(fwd))(*specs)
        except Exception as e:
            if sym_in_specs is not None:
                raise ValueError(
                    "AOT export with dynamic dims "
                    f"{[list(s.shape) for s in sym_in_specs]} failed "
                    "(model not traceable with symbolic shapes: "
                    f"{type(e).__name__}: {e}). Pass concrete "
                    "example_inputs to export a fixed-shape artifact."
                ) from e
            raise
        return write_export_artifacts(
            path_prefix, exported, [f"x{i}" for i in range(len(arrays))],
            (manifest_shapes if manifest_shapes
             else [list(a.shape) for a in arrays]),
            [a.dtype for a in arrays])
    finally:
        if locals().get("converted_patch"):
            layer.__dict__.pop("forward", None)
        if was_training:
            layer.train()


def load_inference_model(path_prefix, executor=None):
    """Returns a Predictor (the fluid triple (program, feed, fetch) has no
    TPU analog — the predictor IS the optimized program).
    Reference: fluid io.py load_inference_model:1411."""
    return Predictor(Config(path_prefix))
