"""paddle.io — Dataset / DataLoader.

Reference parity: python/paddle/fluid/reader.py DataLoader:149 +
dataloader/dataloader_iter.py (multiprocess worker pool, shared-mem queues)
and operators/reader/buffered_reader.cc (double-buffer device prefetch).

TPU-native: host-side loading uses a thread/process pool producing numpy
batches; device prefetch keeps `prefetch_depth` batches in flight via
non-blocking jax.device_put (the buffered_reader analog) so the TPU never
waits on host IO.
"""
from __future__ import annotations

import itertools
import math
import queue
import threading
from typing import Iterable

import numpy as np

from ..framework import random as _random
from ..tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]

    # ship to loader workers as plain numpy: unpickling device arrays in a
    # forkserver/spawn child would import jax there (slow, and the site
    # TPU plugin must never run in a worker); samples re-wrap as Tensors
    # in the parent's collate
    def __getstate__(self):
        return {"tensors": [np.asarray(t.numpy() if isinstance(t, Tensor)
                                       else t) for t in self.tensors]}

    def __setstate__(self, state):
        self.tensors = state["tensors"]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def _host_rng():
    """numpy RandomState chained off the framework RNG: paddle.seed()
    reproduces host-side sampling/shuffling, and test order can't bleed
    through the GLOBAL np.random state (the reference seeds its sampler
    RNGs from op/program seeds the same way).  Each call advances the
    chain, so successive epochs draw different permutations."""
    from ..framework.random import np_random_state

    return np_random_state()


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    perm = _host_rng().permutation(total)
    out, start = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[start:start + ln].tolist()))
        start += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = _host_rng()
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(_host_rng().choice(len(self.weights), self.num_samples,
                                       replace=self.replacement,
                                       p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference: python/paddle/io DistributedBatchSampler — shards the
    dataset across data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[: self.total_size - n]])
        local = indices[self.local_rank::self.nranks]
        batch = []
        for idx in local.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def _numpy_collate(batch):
    """Worker-side collate: numpy-first (device transfer happens in the
    parent; Tensor samples are unwrapped to numpy so only plain arrays
    cross the process queue)."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return [_numpy_collate([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: _numpy_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b.numpy()) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    return batch


def _tensor_wrap(tree):
    """Parent-side: numpy leaves -> Tensor (device transfer boundary)."""
    if isinstance(tree, list):
        return [_tensor_wrap(t) for t in tree]
    if isinstance(tree, dict):
        return {k: _tensor_wrap(v) for k, v in tree.items()}
    if isinstance(tree, np.ndarray):
        return Tensor(tree)
    return tree


class _WorkerError:
    def __init__(self, worker_id, tb):
        self.worker_id = worker_id
        self.traceback = tb


def _worker_loop(dataset, collate_fn, index_queue, result_queue, worker_id,
                 worker_init_fn):
    """Forked worker: fetch + collate in numpy, ship via queue (reference
    dataloader_iter.py _worker_loop)."""
    import traceback
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    use_numpy = collate_fn is default_collate_fn
    while True:
        job = index_queue.get()
        if job is None:
            break
        bid, indices = job
        try:
            samples = [dataset[i] for i in indices]
            batch = (_numpy_collate(samples) if use_numpy
                     else collate_fn(samples))
            result_queue.put((bid, batch))
        except Exception:
            result_queue.put((bid, _WorkerError(worker_id,
                                                traceback.format_exc())))


def pad_ragged(seqs, buckets=None, pad_value=0, dtype=np.int64,
               truncate="tail"):
    """Ragged per-sample sequences → one dense ``[B, L]`` array.

    ``L`` is the smallest entry of ``buckets`` that fits the batch's
    longest sequence (so a handful of XLA shapes serve every batch);
    without buckets, the exact max length.  Sequences beyond the last
    bucket are truncated — ``truncate="tail"`` keeps the last elements
    (the recency convention for click logs), ``"head"`` the first.
    Returns ``(dense, lengths)`` with post-truncation int32 lengths.
    This is numpy-only on purpose: it runs inside collate_fn on the
    DataLoader's prefetch thread.
    """
    cap = None
    if buckets:
        buckets = sorted(int(b) for b in buckets)
        cap = buckets[-1]
    lens = [len(s) if cap is None else min(len(s), cap) for s in seqs]
    width = max(lens) if lens else 1
    if buckets:
        for b in buckets:
            if width <= b:
                width = b
                break
    out = np.full((len(seqs), max(width, 1)), pad_value, dtype)
    for i, s in enumerate(seqs):
        arr = np.asarray(s, dtype)
        if lens[i] < len(arr):
            arr = arr[-lens[i]:] if truncate == "tail" else arr[:lens[i]]
        out[i, :lens[i]] = arr
    return out, np.asarray(lens, np.int32)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(b.numpy()) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        # optional per-batch placement hook (framework.transfer.
        # shard_batch partial): runs on the PREFETCH THREAD, so the
        # async device_put of the next global batch onto its target
        # sharding overlaps device compute of the current one.  Set by
        # Model.fit(mesh=...) for the duration of the fit.
        self.placement = None
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        elif not self._iterable_mode:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
            self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no fixed length")
        return len(self.batch_sampler)

    @staticmethod
    def _placed(gen, place):
        """Apply the placement hook inside the producing generator so it
        executes on whichever thread drives `gen` (the prefetch thread
        when use_buffer_reader is on)."""
        try:
            for item in gen:
                yield place(item)
        finally:
            gen.close()

    def _epoch_batches(self):
        """Materialize this epoch's batch indices ON THE CALLING THREAD.

        The sampler draws its shuffle permutation from the framework RNG
        chain, which is THREAD-LOCAL (framework/random.py): iterating
        the sampler lazily inside the buffered-reader prefetch thread
        would pull the permutation from that thread's own never-seeded
        chain, so `paddle.seed()` silently stopped controlling shuffle
        order (and buffered vs unbuffered loaders shuffled differently).
        Drawing here — the consumer's thread, before the prefetch thread
        exists — restores the seeded, thread-agnostic contract.

        Only the framework's own BatchSampler (incl. subclasses) is
        materialized this way: it is the sampler that draws from the
        framework chain, and it is len-bounded by construction.  A
        user-supplied batch_sampler may be generator-backed or infinite,
        so it keeps its lazy streaming contract (see __iter__)."""
        return [list(b) for b in self.batch_sampler]

    def _produce(self, idx_batches=None):
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not getattr(self, "drop_last", False):
                yield self.collate_fn(batch)
            return
        if self.num_workers > 0:
            # worker dispatch needs the full index list up front (round-
            # robin + reorder) — same as before the RNG fix
            yield from self._produce_multiprocess(
                idx_batches if idx_batches is not None
                else [list(b) for b in self.batch_sampler])
            return
        for idx_batch in (idx_batches if idx_batches is not None
                          else self.batch_sampler):
            yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def _pick_start_method(self):
        """forkserver by default: fork() in a JAX process (multithreaded)
        is a documented deadlock risk and warns on every worker start.
        forkserver workers descend from a clean helper process that never
        imported jax. Requires a picklable dataset/collate/init_fn — a
        preflight checks this and falls back to fork with a warning
        (reference worker model pickles too: dataloader_iter.py).
        Override with PADDLE_TPU_MP_START=fork|forkserver|spawn."""
        import multiprocessing as mp
        import os
        import pickle

        env = os.environ.get("PADDLE_TPU_MP_START", "").strip().lower()
        if env:
            return env
        cached = getattr(self, "_mp_start_cache", None)
        if cached is not None:
            return cached

        class _CapHit(Exception):
            pass

        class _NullSink:
            # stream to nowhere with a byte cap: the preflight only needs
            # to know whether pickling FAILS (lambdas, locks — which fail
            # early), not the bytes.  pickle.dumps of a large in-memory
            # dataset would burn CPU and transiently hold the whole
            # serialization (round-3 advisor finding).
            def __init__(self, cap=64 << 20):
                self.n, self.cap = 0, cap

            def write(self, b):
                self.n += len(b)
                if self.n > self.cap:
                    raise _CapHit

        try:
            # fns first and UNCAPPED: they are tiny, and the usual
            # unpicklables (lambdas, bound methods) live here — a huge
            # dataset must not cap the probe before they are reached
            pickle.Pickler(_NullSink(cap=1 << 62)).dump(
                (self.collate_fn, self.worker_init_fn))
            pickle.Pickler(_NullSink()).dump(self.dataset)
        except _CapHit:
            pass  # huge but structurally picklable: forkserver is fine
        except Exception:
            import warnings
            warnings.warn(
                "DataLoader dataset/collate_fn/worker_init_fn is not "
                "picklable; falling back to fork-based workers (deadlock "
                "risk in multithreaded processes). Define them at module "
                "scope to enable forkserver workers.", RuntimeWarning)
            self._mp_start_cache = "fork"
            return "fork"
        method = ("forkserver"
                  if "forkserver" in mp.get_all_start_methods() else "spawn")
        self._mp_start_cache = method
        return method

    def _produce_multiprocess(self, idx_batches):
        """Multi-process map-style loading (reference:
        fluid/reader.py dataloader_iter.py _DataLoaderIterMultiProcess:478 —
        worker pool + result reordering).  Workers do numpy-only work
        (fetch + collate); device transfer stays in the main process, the
        process boundary for XLA."""
        import multiprocessing as mp
        import os

        ctx = mp.get_context(self._pick_start_method())
        index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        result_queue = ctx.Queue()
        workers = []
        # Workers must never touch the accelerator: a child re-importing
        # jax through the site TPU plugin would dial the tunnel the parent
        # holds and hang. Env is captured at child (and forkserver-server)
        # start, so pin it around the spawn window: force-CPU AND disable
        # the tunnel plugin registration outright.
        prev = {k: os.environ.get(k)
                for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            for wid, iq in enumerate(index_queues):
                w = ctx.Process(
                    target=_worker_loop,
                    args=(self.dataset, self.collate_fn, iq, result_queue,
                          wid, self.worker_init_fn),
                    daemon=True)
                w.start()
                workers.append(w)
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        try:
            batches = idx_batches
            # dispatch round-robin, keep prefetch_factor per worker in flight
            next_send = 0
            max_inflight = self.num_workers * self.prefetch_factor
            reorder: dict[int, object] = {}
            next_yield = 0
            user_timeout = self.timeout if self.timeout > 0 else None
            import time as _time

            def send_one():
                nonlocal next_send
                if next_send < len(batches):
                    index_queues[next_send % self.num_workers].put(
                        (next_send, batches[next_send]))
                    next_send += 1

            def recv_one():
                """Poll the result queue, detecting dead workers (a
                segfaulted/OOM-killed worker would otherwise hang the
                loader forever) and honoring the user timeout."""
                deadline = (None if user_timeout is None
                            else _time.monotonic() + user_timeout)
                while True:
                    try:
                        return result_queue.get(timeout=1.0)
                    except queue.Empty:
                        pass
                    for w in workers:
                        if not w.is_alive() and w.exitcode != 0:
                            raise RuntimeError(
                                f"DataLoader worker pid={w.pid} died with "
                                f"exit code {w.exitcode}. If this "
                                "happened at startup, the launching "
                                "script probably lacks an `if __name__ "
                                "== '__main__':` guard — forkserver/"
                                "spawn workers re-import the main module "
                                "(same contract as torch DataLoader on "
                                "spawn platforms). Guard the script, or "
                                "set PADDLE_TPU_MP_START=fork to opt "
                                "back into fork workers (deadlock risk "
                                "in multithreaded/JAX processes).")
                    if deadline is not None and _time.monotonic() > deadline:
                        raise RuntimeError(
                            f"DataLoader worker timed out after "
                            f"{self.timeout}s")

            for _ in range(min(max_inflight, len(batches))):
                send_one()
            while next_yield < len(batches):
                if next_yield in reorder:
                    batch = reorder.pop(next_yield)
                    next_yield += 1
                    from .. import core as _core
                    _core.stat_add("dataloader.batches")
                    if self.collate_fn is default_collate_fn:
                        batch = _tensor_wrap(batch)
                    yield batch
                    send_one()
                    continue
                bid, payload = recv_one()
                if isinstance(payload, _WorkerError):
                    raise RuntimeError(
                        f"DataLoader worker {payload.worker_id} failed:\n"
                        f"{payload.traceback}")
                reorder[bid] = payload
        finally:
            for iq in index_queues:
                iq.put(None)
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()

    def __iter__(self):
        # sampler permutation drawn HERE (the thread CALLING iter(),
        # i.e. the seeded consumer) — never lazily on the prefetch
        # thread; see _epoch_batches.  A plain method (not a generator
        # function) so the draw happens at iter() time, not deferred to
        # the first next(), which a prefetch wrapper could run on an
        # unseeded thread.  User-supplied batch_samplers stay lazy:
        # they may be generator-backed/infinite, and they don't draw
        # from the framework chain, so eager materialization would only
        # break them without fixing anything.
        idx_batches = (self._epoch_batches()
                       if isinstance(self.batch_sampler, BatchSampler)
                       else None)
        return self._iter_impl(idx_batches)

    def _iter_impl(self, idx_batches):
        gen = self._produce(idx_batches)
        place = self.placement
        if place is not None:
            gen = self._placed(gen, place)
        if not self.use_buffer_reader:
            yield from gen
            return
        # double-buffered prefetch on a background thread
        # (operators/reader/buffered_reader.cc analog)
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor)
        sentinel = object()
        stop = threading.Event()

        def put_or_stop(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in gen:
                    if not put_or_stop(item):
                        return
                put_or_stop(sentinel)
            except BaseException as e:  # re-raised in the consumer
                put_or_stop(e)
            finally:
                # run the source generator's cleanup (worker-process
                # shutdown) in ITS OWN thread — the consumer abandoning
                # iteration early must not leak worker processes
                gen.close()

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            t.join(timeout=10)


def get_worker_info():
    return None  # single-process host loading; workers are threads
