"""paddle.jit — to_static / save / load.

Reference parity: python/paddle/fluid/dygraph/jit.py (@declarative,
TracedLayer) and dygraph_to_static/program_translator.py:233 StaticFunction.

TPU-native: there is no AST rewriting — jax tracing IS program capture.
`to_static(fn)` returns a StaticFunction that jit-compiles the function with
the owning Layer's parameters/buffers passed as *arguments* (swapped in via
the layer_base functional bridge), so later in-place param updates
(optimizer.step) are picked up without recompilation — the same contract as
the reference's partial_program parameter binding.  Input-shape-keyed compile
caching comes from jax.jit itself (≙ ConcreteProgram cache keyed on
InputSpec, program_translator.py:719).
"""
from __future__ import annotations

import logging
import os
import pickle

import jax
import numpy as np

from ..autograd import suspend_tape
from ..framework import random as _random
from ..nn.layer_base import Layer, _swapped_state, state_pytrees
from ..tensor import Tensor

logger = logging.getLogger("paddle_tpu.jit")


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = shape
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


class StaticFunction:
    """Compiled callable. If the target is a Layer method, parameters and
    buffers are jit arguments (not baked constants)."""

    def __init__(self, function, input_spec=None):
        self._input_spec = input_spec
        self._layer = None
        if isinstance(function, Layer):
            self._layer = function
            self._method = type(function).forward
        elif hasattr(function, "__self__") and isinstance(function.__self__, Layer):
            self._layer = function.__self__
            self._method = function.__func__
        else:
            self._method = function
        self._raw_method = self._method
        self._method = _maybe_convert(self._method)
        self._build_compiled()
        # a second compiled path for ProgramTranslator.enable(False):
        # the reference toggles dy2static dynamically per call
        self._compiled_converted = self._compiled
        self._compiled_raw = None

    def _build_compiled(self):
        layer = self._layer
        method = self._method
        if layer is not None:
            @jax.jit
            def compiled(params, buffers, rng, args, kwargs):
                with suspend_tape(), _random.rng_guard(rng), \
                        _swapped_state(layer, params, buffers) as bmap:
                    out = method(layer, *args, **kwargs)
                    new_buffers = {k: t.value for k, t in bmap.items()}
                return out, new_buffers
        else:
            @jax.jit
            def compiled(rng, args, kwargs):
                with suspend_tape(), _random.rng_guard(rng):
                    return method(*args, **kwargs)

        self._compiled = compiled

    def __get__(self, instance, owner):
        if instance is None:
            return self
        key = "_jit_cache_" + self._method.__name__
        cached = instance.__dict__.get(key)
        if cached is None:
            cached = StaticFunction(self._method.__get__(instance),
                                    self._input_spec)
            instance.__dict__[key] = cached
        return cached

    def _active_compiled(self):
        if ProgramTranslator._enabled or self._method is self._raw_method:
            return self._compiled_converted
        if self._compiled_raw is None:
            conv = self._method
            self._method = self._raw_method
            try:
                self._build_compiled()
                self._compiled_raw = self._compiled
            finally:
                self._method = conv
                self._compiled = self._compiled_converted
        return self._compiled_raw

    def __call__(self, *args, **kwargs):
        rng = _random.split_key()
        compiled = self._active_compiled()
        if self._layer is not None:
            params, buffers = state_pytrees(self._layer)
            out, new_buffers = compiled(params, buffers, rng, args,
                                        kwargs)
            bmap = dict(self._layer.named_buffers())
            for name, val in new_buffers.items():
                bmap[name]._value = val
            return out
        return compiled(rng, args, kwargs)

    @property
    def inner_function(self):
        return self._method


def _maybe_convert(method):
    """AST-convert data-dependent python control flow onto static.nn
    cond/while_loop (reference program_translator.py:233 → ast_transformer);
    untransformable sources fall back to plain tracing, the reference's
    behavior for unconvertible code."""
    if getattr(method, "__not_to_static__", False) or \
            getattr(method, "__dy2static__", False):
        return method
    if not ProgramTranslator._enabled:
        return method  # ProgramTranslator.enable(False): plain tracing
    from . import dy2static

    try:
        converted = dy2static.convert_function(method)
        if _LOG_LEVELS["code_level"] > 0 and \
                getattr(converted, "__converted_source__", None):
            logger.info("[dy2static] transformed code of %s:\n%s",
                        getattr(method, "__qualname__", method),
                        converted.__converted_source__)
        return converted
    except dy2static.BenignNoConversion:
        return method  # nothing to convert: plain tracing is not a hazard
    except dy2static.ConversionError as e:
        import warnings

        warnings.warn(
            f"to_static: AST conversion of "
            f"{getattr(method, '__qualname__', method)} failed ({e}); "
            "falling back to plain tracing — any tensor-dependent python "
            "`if`/`while` in it will be baked to the traced branch",
            stacklevel=3)
        return method


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    def deco(fn):
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn, input_spec)
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return deco(function)
    return deco


declarative = to_static  # fluid-era alias


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


def save(layer, path, input_spec=None, **config):
    """Serialize a Layer (architecture via pickle + weights as numpy arrays).
    Reference: paddle.jit.save → TranslatedLayer artifact
    (.pdmodel/.pdiparams); AOT compilation is served by jax.export in
    paddle_tpu.inference."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {k: np.asarray(v.numpy()) for k, v in layer.state_dict().items()}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f)
    # a to_static'd layer carries an unpicklable instance-level
    # StaticFunction in `forward`; the pickle artifact stores the plain
    # dygraph layer (the compiled graph lives in the AOT .pdexport path)
    overrides = {
        k: layer.__dict__.pop(k) for k in list(layer.__dict__)
        if isinstance(layer.__dict__[k], StaticFunction)
        or k.startswith("_jit_cache_")
    }
    try:
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(layer, f)
    finally:
        layer.__dict__.update(overrides)


def load(path, **config):
    with open(path + ".pdmodel", "rb") as f:
        layer = pickle.load(f)
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    layer.set_state_dict(state)
    return layer


class TracedLayer:
    """Reference: fluid/dygraph/jit.py TracedLayer (trace once, run static)."""

    def __init__(self, layer, static_fn):
        self._layer = layer
        self._fn = static_fn

    @staticmethod
    def trace(layer, inputs):
        sf = StaticFunction(layer)
        out = sf(*inputs)
        return out, TracedLayer(layer, sf)

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)

    def save_inference_model(self, path, feed=None, fetch=None):
        save(self._layer, path)


class TranslatedLayer:
    """Type alias contract (fluid/dygraph/io.py TranslatedLayer): what
    jit.load returns.  Here jit.load reconstructs the ORIGINAL Layer
    class (pickled module-scope class + state dict), which is strictly
    richer than the reference's program-backed shell; this name exists
    for isinstance-style compatibility."""

    def __new__(cls, *a, **k):
        raise TypeError(
            "TranslatedLayer is not constructed directly; use "
            "paddle.jit.load(path)")


_LOG_LEVELS = {"verbosity": 0, "code_level": 0}


def set_verbosity(level=0, also_to_stdout=False):
    """dy2static logging verbosity (jit/set_verbosity): stored and
    exposed; conversion warnings always go through warnings.warn."""
    _LOG_LEVELS["verbosity"] = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """dy2static transformed-code printing (jit/set_code_level): at any
    level > 0, convert_function prints the recompiled source."""
    _LOG_LEVELS["code_level"] = int(level)


class ProgramTranslator:
    """Singleton switch for dy2static (dygraph_to_static/
    program_translator.py ProgramTranslator): enable(False) makes
    to_static fall back to plain tracing."""

    _instance = None
    _enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static=True):
        type(self)._enabled = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return type(self)._enabled


# submodule export (reference jit/__init__.py: `from . import dy2static`)
from . import dy2static  # noqa: E402,F401
