"""dygraph→static AST conversion of data-dependent python control flow.

Reference parity: python/paddle/fluid/dygraph/dygraph_to_static/
(program_translator.py:233 StaticFunction.__call__ → ast_transformer.py
DygraphToStaticAst; convert_operators.py convert_ifelse/convert_while).
The reference rewrites python ``if``/``while``/``for`` over tensors into
cond/while program ops; here the same source rewrite targets
``static.nn.cond`` / ``static.nn.while_loop``, which lower to XLA's
structured control flow — so a to_static'd model with data-dependent
branching compiles into ONE jitted program with both branches live.

Architecture (mirrors the reference's two halves, re-designed for jax):

* AST pass (:class:`ControlFlowTransformer`): turns each ``if``/``while``/
  ``for range()`` statement into nested closures plus a call to a runtime
  dispatch helper. Writes inside a branch/loop-body become function
  parameters + returns (closure conversion); reads come for free from
  python's lexical scoping.
* runtime dispatch (``_jst_if`` / ``_jst_while``): checks whether the
  predicate is a traced/jax value at RUN time — tensor predicates route to
  ``static.nn.cond``/``while_loop`` (compiled, both branches live), plain
  python values run as ordinary python (the reference's
  convert_operators.py:40 does exactly this dispatch).

``break``/``continue`` in converted loops are supported by flag
elimination (the reference's break_continue_transformer.py analog): each
``break`` becomes a persistent flag that is AND-ed into the loop
condition, each ``continue`` a per-iteration flag, and the statements
after the branch are guarded on the flags.  Remaining unsupported
constructs (mixed return/fall-through branches, break inside with/try)
raise ConversionError; ``to_static`` then falls back to plain tracing
WITH a warning naming the construct (round-3 verdict: the silent
fallback could single-branch-bake a user's data-dependent branch).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types


class ConversionError(Exception):
    """Source can't be converted; caller falls back to plain tracing."""


class BenignNoConversion(ConversionError):
    """No conversion applicable (no control flow / no source): the plain
    tracing fallback is not a behavior hazard, so no warning is due."""


_UNDEF = object()  # placeholder for branch-local names unbound at entry


def _is_traced(x):
    import jax

    from ..tensor import Tensor

    if isinstance(x, Tensor):
        x = x.value
    return isinstance(x, (jax.Array, jax.core.Tracer))


def _jst_bool(pred):
    """Python truthiness for non-tensor predicates."""
    return bool(pred)


def _jst_if(pred, true_fn, false_fn, init_vals):
    """convert_ifelse analog: tensor pred → static.nn.cond with both
    branches traced; python pred → plain dispatch."""
    if not _is_traced(pred):
        return true_fn(*init_vals) if pred else false_fn(*init_vals)
    from ..static import nn as snn

    out = snn.cond(pred, lambda: _check_defined(true_fn(*init_vals)),
                   lambda: _check_defined(false_fn(*init_vals)))
    return out


def _check_defined(vals):
    if isinstance(vals, tuple):
        for v in vals:
            if v is _UNDEF:
                raise ConversionError(
                    "a variable assigned in only one branch of a converted "
                    "`if` is used afterwards; assign it in both branches "
                    "(or before the if) for tensor-predicate conversion")
    return vals


def _jst_while(cond_fn, body_fn, loop_vars):
    """convert_while analog: tensor condition → static.nn.while_loop;
    python condition → ordinary loop."""
    first = cond_fn(*loop_vars)
    if not _is_traced(first) and not any(_is_traced(v) for v in loop_vars):
        vals = tuple(loop_vars)
        while cond_fn(*vals):
            out = body_fn(*vals)
            vals = out if isinstance(out, tuple) else (out,)
        return vals
    from ..static import nn as snn

    if any(v is _UNDEF for v in loop_vars):
        raise ConversionError(
            "a loop variable of a tensor-bounded converted loop is not "
            "defined before the loop; initialize loop-local temporaries "
            "before `while`/`for` when the trip count is a tensor")
    return tuple(snn.while_loop(cond_fn, body_fn, tuple(loop_vars)))


class _StoreCollector(ast.NodeVisitor):
    """Names assigned (stored) in a statement list, in first-seen order.
    Does not descend into nested function/class definitions."""

    def __init__(self):
        self.names: list[str] = []

    def _add(self, n):
        if n not in self.names:
            self.names.append(n)

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self._add(node.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            self._add(node.target.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # the def binds the name; don't descend.  Our own closure-conversion
        # helpers (__jst_*) are never carried as branch/loop outputs —
        # functions aren't jax values — but USER defs keep the old
        # behavior: carrying them works on the python dispatch path and
        # raises ConversionError (→ fallback) on the traced path.
        if not node.name.startswith("__jst_"):
            self._add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._add(node.name)

    def visit_Lambda(self, node):
        pass


def _stores(stmts) -> list[str]:
    c = _StoreCollector()
    for s in stmts:
        c.visit(s)
    return c.names


def _has(stmts, *types) -> bool:
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, types):
                return True
    return False


def _has_shallow(stmts, *ts) -> bool:
    """Like _has but never descends into nested function/class defs: a
    `return` (or break/continue) there belongs to the nested scope — in
    particular to the closure-conversion helpers this module generates."""
    for s in stmts or []:
        if isinstance(s, ts):
            return True
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        for _, value in ast.iter_fields(s):
            if isinstance(value, list) and value and isinstance(
                    value[0], (ast.stmt, ast.excepthandler)):
                if _has_shallow(value, *ts):
                    return True
    return False


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _try_eval_expr(var: str):
    # _jst_maybe(lambda: var) — returns _UNDEF when the name is unbound
    return ast.Call(
        func=_name("_jst_maybe"),
        args=[ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=_name(var))],
        keywords=[])


def _jst_maybe(thunk):
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return _UNDEF


class ControlFlowTransformer(ast.NodeTransformer):
    """Closure-converts if/while/for-range statements into dispatch-helper
    calls (the DygraphToStaticAst analog)."""

    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- helpers ----------------------------------------------------------

    def _fn_def(self, name, params, body, returns):
        """def name(p0, p1, ...):  <body>;  return (r0, r1, ...)"""
        body = list(body)
        if returns is not None:
            ret_val = (ast.Tuple(elts=[_name(r) for r in returns],
                                 ctx=ast.Load())
                       if len(returns) != 1 else _name(returns[0]))
            body.append(ast.Return(value=ret_val))
        if not body:
            body = [ast.Pass()]
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=p) for p in params],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=body, decorator_list=[])

    def _assign_targets(self, names, value):
        tgt = (ast.Tuple(elts=[_name(n, ast.Store()) for n in names],
                         ctx=ast.Store())
               if len(names) != 1 else _name(names[0], ast.Store()))
        return ast.Assign(targets=[tgt], value=value)

    # -- if ---------------------------------------------------------------

    def visit_If(self, node):
        node = self._generic_body_visit(node)
        body, orelse = node.body, node.orelse

        body_returns = _has_shallow(body, ast.Return)
        else_returns = _has_shallow(orelse, ast.Return) if orelse else False
        if body_returns or else_returns:
            # only the uniform shape `if c: return a [else: return b]`
            # (return as the final statement of each branch) converts;
            # `if c: return a` + trailing statements was merged into this
            # shape by _merge_tail_returns before transformation
            def _ret_ok(stmts):
                return (stmts and isinstance(stmts[-1], ast.Return)
                        and not _has_shallow(stmts[:-1], ast.Return))

            if not orelse or not (_ret_ok(body) and _ret_ok(orelse)):
                raise ConversionError(
                    "mixed return/fall-through in converted `if`")
            t_body, f_body = body, orelse
            uid = self._uid()
            tfn, ffn = f"__jst_true_{uid}", f"__jst_false_{uid}"
            t_def = ast.FunctionDef(
                name=tfn, args=ast.arguments(
                    posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[],
                    defaults=[]),
                body=t_body, decorator_list=[])
            f_def = ast.FunctionDef(
                name=ffn, args=ast.arguments(
                    posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[],
                    defaults=[]),
                body=f_body, decorator_list=[])
            call = ast.Call(func=_name("_jst_if"),
                            args=[node.test,
                                  _name(tfn), _name(ffn),
                                  ast.Tuple(elts=[], ctx=ast.Load())],
                            keywords=[])
            return [t_def, f_def, ast.Return(value=call)]

        out_vars = sorted(set(_stores(body)) | set(_stores(orelse)))
        if not out_vars:
            # side-effect-only branches (e.g. list.append) can't convert;
            # leave as python `if` — works for python preds, traced preds
            # will raise TracerBoolConversionError at jit time, matching
            # the un-converted baseline
            return node
        uid = self._uid()
        tfn, ffn = f"__jst_true_{uid}", f"__jst_false_{uid}"
        t_def = self._fn_def(tfn, out_vars, body, out_vars)
        f_def = self._fn_def(ffn, out_vars, orelse, out_vars)
        init = ast.Tuple(elts=[_try_eval_expr(v) for v in out_vars],
                         ctx=ast.Load())
        call = ast.Call(func=_name("_jst_if"),
                        args=[node.test, _name(tfn), _name(ffn), init],
                        keywords=[])
        return [t_def, f_def, self._assign_targets(out_vars, call)]

    # -- while ------------------------------------------------------------

    def _eliminate_loop_bc(self, body):
        """Flag-eliminate this loop's break/continue BEFORE closure
        conversion (the guard `if`s it creates must themselves be
        converted).  Returns (pre_stmts, new_body, test_wrapper)."""
        if not _bc_tops(body):
            return [], body, lambda t: t
        uid = self._uid()
        brk, cont = f"__jst_brk_{uid}", f"__jst_cont_{uid}"
        new, used_b, used_c = _eliminate_bc(body, brk, cont)
        if _bc_tops(new):
            raise ConversionError(
                "break/continue inside with/try in a converted loop")
        pre, top = [], []
        if used_c:
            # reset each iteration; pre-init so it is a defined loop var
            top.append(_assign_const(cont, False))
            pre.append(_assign_const(cont, False))
        if used_b:
            pre.append(_assign_const(brk, False))
            # _jst_land_lazy(not brk, lambda: test): the user condition
            # must NOT be re-evaluated once break fired on the python
            # path (it may index past the break point)
            return pre, top + new, (lambda t: ast.Call(
                func=_name("_jst_land_lazy"),
                args=[ast.Call(func=_name("_jst_lnot"),
                               args=[_name(brk)], keywords=[]),
                      ast.Lambda(
                          args=ast.arguments(
                              posonlyargs=[], args=[], kwonlyargs=[],
                              kw_defaults=[], defaults=[]),
                          body=t)],
                keywords=[]))
        return pre, top + new, (lambda t: t)

    def visit_While(self, node):
        if node.orelse:
            raise ConversionError("while/else does not convert")
        pre_bc, new_body, wrap = self._eliminate_loop_bc(node.body)
        node.body = new_body
        node.test = wrap(node.test)
        if _has_shallow(node.body, ast.Return):
            raise ConversionError("return inside a converted while loop")
        node = self._generic_body_visit(node)
        loop_vars = _stores(node.body)
        if not loop_vars:
            return node
        uid = self._uid()
        cfn, bfn = f"__jst_cond_{uid}", f"__jst_body_{uid}"
        c_def = self._fn_def(cfn, loop_vars,
                             [ast.Return(value=node.test)], None)
        b_def = self._fn_def(bfn, loop_vars, node.body, loop_vars)
        init = ast.Tuple(elts=[_try_eval_expr(v) for v in loop_vars],
                         ctx=ast.Load())
        call = ast.Call(func=_name("_jst_while"),
                        args=[_name(cfn), _name(bfn), init], keywords=[])
        return pre_bc + [c_def, b_def,
                         self._assign_targets(loop_vars, call)]

    # -- for i in range(...) ---------------------------------------------

    def visit_For(self, node):
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and 1 <= len(node.iter.args) <= 3
                    and not node.iter.keywords)
        if not is_range or not isinstance(node.target, ast.Name):
            # generic iterables stay python (unrolled if traced);
            # break/continue inside belong to the python loop
            return self._generic_body_visit(node)
        if node.orelse:
            raise ConversionError("for/else does not convert")
        # eliminate break/continue on the USER body only, so the index
        # increment appended below stays outside the continue guard
        pre_bc, new_body, wrap = self._eliminate_loop_bc(node.body)
        node.body = new_body
        if _has_shallow(node.body, ast.Return):
            raise ConversionError("return inside a converted for loop")
        node = self._generic_body_visit(node)
        uid = self._uid()
        it, stop, step = (f"__jst_it_{uid}", f"__jst_stop_{uid}",
                          f"__jst_step_{uid}")
        a = node.iter.args
        if len(a) == 1:
            start_e, stop_e, step_e = ast.Constant(0), a[0], ast.Constant(1)
        elif len(a) == 2:
            start_e, stop_e, step_e = a[0], a[1], ast.Constant(1)
        else:
            start_e, stop_e, step_e = a
        pre = [
            ast.Assign(targets=[_name(it, ast.Store())], value=start_e),
            ast.Assign(targets=[_name(stop, ast.Store())], value=stop_e),
            ast.Assign(targets=[_name(step, ast.Store())], value=step_e),
            # pre-bind the target so it is a defined loop var on the
            # traced path (python leaves it unbound for empty ranges;
            # harmless deviation)
            ast.Assign(targets=[_name(node.target.id, ast.Store())],
                       value=_name(it)),
        ]
        # while __it*sign < __stop*sign:  i = __it; <body>; __it += __step
        sign = ast.Call(func=_name("_jst_sign"), args=[_name(step)],
                        keywords=[])
        test = ast.Compare(
            left=ast.BinOp(left=_name(it), op=ast.Mult(), right=sign),
            ops=[ast.Lt()],
            comparators=[ast.BinOp(left=_name(stop), op=ast.Mult(),
                                   right=sign)])
        body = ([ast.Assign(targets=[_name(node.target.id, ast.Store())],
                            value=_name(it))]
                + node.body
                + [ast.AugAssign(target=_name(it, ast.Store()),
                                 op=ast.Add(), value=_name(step))])
        wh = ast.While(test=wrap(test), body=body, orelse=[])
        out = pre_bc + pre + self.visit_While(wh)
        return out

    def _generic_body_visit(self, node):
        """Recurse into child statement lists first (depth-first)."""
        for field in ("body", "orelse"):
            stmts = getattr(node, field, None)
            if stmts is None:
                continue
            stmts = _merge_tail_returns(stmts)
            new = []
            for s in stmts:
                r = self.visit(s) if isinstance(
                    s, (ast.If, ast.While, ast.For)) else s
                new.extend(r if isinstance(r, list) else [r])
            setattr(node, field, new)
        return node


def _jst_sign(step):
    import jax.numpy as jnp

    if _is_traced(step):
        return jnp.sign(step)
    return 1 if step >= 0 else -1


def _jst_raw(x):
    from ..tensor import Tensor

    return x.value if isinstance(x, Tensor) else x


def _jst_lnot(x):
    import jax.numpy as jnp

    return jnp.logical_not(_jst_raw(x)) if _is_traced(x) else (not x)


def _jst_lor(a, b):
    import jax.numpy as jnp

    if _is_traced(a) or _is_traced(b):
        return jnp.logical_or(_jst_raw(a), _jst_raw(b))
    return a or b


def _jst_land(a, b):
    import jax.numpy as jnp

    if _is_traced(a) or _is_traced(b):
        return jnp.logical_and(_jst_raw(a), _jst_raw(b))
    return a and b


def _jst_land_lazy(a, b_thunk):
    """Short-circuit AND: b_thunk is only evaluated when a is traced or
    truthy (python `a and b()` semantics for the loop-condition wrapper)."""
    if not _is_traced(a) and not a:
        return False
    return _jst_land(a, b_thunk())


# -- break/continue elimination (break_continue_transformer.py analog) ----

def _assign_const(name, val):
    return ast.Assign(targets=[_name(name, ast.Store())],
                      value=ast.Constant(val))


def _bc_tops(stmts):
    """break/continue statements belonging to the CURRENT loop: descends
    ifs and with/try (those are detection-only), never nested loops or
    function definitions."""
    for s in stmts or []:
        if isinstance(s, (ast.Break, ast.Continue)):
            return True
        if isinstance(s, ast.If):
            if _bc_tops(s.body) or _bc_tops(s.orelse):
                return True
        elif isinstance(s, (ast.With, ast.AsyncWith, ast.Try)):
            for field in ("body", "orelse", "finalbody"):
                if _bc_tops(getattr(s, field, None)):
                    return True
            for h in getattr(s, "handlers", ()):
                if _bc_tops(h.body):
                    return True
    return False


def _eliminate_bc(body, brk, cont):
    """Rewrite break/continue into flag assignments; statements after a
    flag-setting `if` are wrapped in a guard on the flags.  Returns
    (new_body, used_break, used_continue).  break/continue inside
    with/try are left in place (caller raises ConversionError)."""
    new, used_b, used_c = [], False, False
    for i, s in enumerate(body):
        if isinstance(s, ast.Break):
            new.append(_assign_const(brk, True))
            return new, True, used_c          # rest is unreachable
        if isinstance(s, ast.Continue):
            new.append(_assign_const(cont, True))
            return new, used_b, True
        if isinstance(s, ast.If) and (_bc_tops(s.body) or _bc_tops(s.orelse)):
            nb, b1, c1 = _eliminate_bc(s.body, brk, cont)
            no, b2, c2 = _eliminate_bc(s.orelse, brk, cont)
            used_b |= b1 or b2
            used_c |= c1 or c2
            newif = ast.If(test=s.test, body=nb, orelse=no)
            ast.copy_location(newif, s)
            new.append(newif)
            rest, b3, c3 = _eliminate_bc(body[i + 1:], brk, cont)
            used_b |= b3
            used_c |= c3
            flags = ([brk] if (b1 or b2) else []) + \
                    ([cont] if (c1 or c2) else [])
            if rest and not flags:
                # the if held break/continue only inside with/try (left
                # untransformed): no guard needed; the caller's leftover
                # check raises ConversionError
                new.extend(rest)
            elif rest:
                t = (_name(flags[0]) if len(flags) == 1
                     else ast.Call(func=_name("_jst_lor"),
                                   args=[_name(flags[0]), _name(flags[1])],
                                   keywords=[]))
                guard = ast.If(
                    test=ast.Call(func=_name("_jst_lnot"), args=[t],
                                  keywords=[]),
                    body=rest, orelse=[])
                ast.copy_location(guard, s)
                new.append(guard)
            return new, used_b, used_c
        new.append(s)
    return new, used_b, used_c


class _SuperRewriter(ast.NodeTransformer):
    """Rewrite zero-arg ``super()`` into ``super(__class__, <self>)``:
    the recompiled function is no longer syntactically inside its class,
    so CPython will not synthesize the ``__class__`` cell — the explicit
    reference makes ``__class__`` an ordinary freevar that
    convert_function re-links to the original class cell (round-3
    advisor finding: zero-arg super() raised RuntimeError at call)."""

    def __init__(self, first_arg):
        self.first = first_arg
        self.used = False

    def visit_Call(self, node):
        self.generic_visit(node)
        if (isinstance(node.func, ast.Name) and node.func.id == "super"
                and not node.args and not node.keywords):
            if self.first is None:
                raise ConversionError(
                    "zero-arg super() in a function with no positional "
                    "parameters")
            self.used = True
            return ast.copy_location(
                ast.Call(func=node.func,
                         args=[_name("__class__"), _name(self.first)],
                         keywords=[]), node)
        return node

    def visit_FunctionDef(self, node):
        return node  # nested defs keep their own super() semantics

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node


def convert_function(fn):
    """Return an AST-converted version of `fn` (data-dependent python
    control flow → static.nn dispatch), or raise ConversionError."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise BenignNoConversion(f"source unavailable: {e}") from e
    try:
        tree = ast.parse(src)
    except SyntaxError as e:  # e.g. lambda fragment
        raise BenignNoConversion(f"unparsable source: {e}") from e
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise BenignNoConversion("not a function definition")
    if not _has(fdef.body, ast.If, ast.While, ast.For):
        raise BenignNoConversion("no control flow to convert")
    # only the to_static family may be stripped: recompiling drops every
    # decorator, so anything else (lru_cache, staticmethod, user wrappers)
    # would silently lose behavior (round-3 advisor finding)
    for dec in fdef.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = d.attr if isinstance(d, ast.Attribute) else getattr(d, "id",
                                                                   None)
        if name not in ("to_static", "not_to_static"):
            raise ConversionError(
                f"decorator @{ast.unparse(dec)} would be dropped by AST "
                "recompilation")
    fdef.decorator_list = []  # strip @to_static etc. to avoid recursion

    pos_args = [a.arg for a in fdef.args.posonlyargs + fdef.args.args]
    sup = _SuperRewriter(pos_args[0] if pos_args else None)
    fdef.body = [sup.visit(s) for s in fdef.body]
    if sup.used and "__class__" not in fn.__code__.co_freevars:
        raise ConversionError(
            "zero-arg super() outside a class-body method")

    tr = ControlFlowTransformer()
    new_body = []
    # `if c: return a` + following statements first becomes if/else with
    # the remainder as the else branch (ReturnTransformer analog), so the
    # both-branches-return conversion applies
    for s in _merge_tail_returns(fdef.body):
        r = tr.visit(s) if isinstance(s, (ast.If, ast.While, ast.For)) else s
        new_body.extend(r if isinstance(r, list) else [r])
    fdef.body = new_body
    ast.fix_missing_locations(tree)

    glb = dict(fn.__globals__)
    glb.update(_jst_if=_jst_if, _jst_while=_jst_while,
               _jst_maybe=_jst_maybe, _jst_sign=_jst_sign,
               _jst_bool=_jst_bool, _jst_lnot=_jst_lnot,
               _jst_lor=_jst_lor, _jst_land=_jst_land,
               _jst_land_lazy=_jst_land_lazy)
    freevars = list(fn.__code__.co_freevars)
    if freevars:
        # Recompile inside a synthetic enclosing scope whose params shadow
        # the freevars, then re-link the inner code object to the ORIGINAL
        # cells: late-binding closure semantics and zero-arg super()
        # survive conversion (round-3 advisor finding: snapshotting cells
        # into globals lost both).
        maker = ast.FunctionDef(
            name="__jst_make__",
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in freevars],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[fdef, ast.Return(value=_name(fdef.name))],
            decorator_list=[])
        tree.body = [maker]
        ast.fix_missing_locations(tree)
        code = compile(tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        ns: dict = {}
        exec(code, glb, ns)
        vals = []
        for cell in fn.__closure__:
            try:
                vals.append(cell.cell_contents)
            except ValueError:
                vals.append(None)  # not-yet-filled cell; re-linked below
        made = ns["__jst_make__"](*vals)
        cellmap = dict(zip(freevars, fn.__closure__))
        out = types.FunctionType(
            made.__code__, glb, fn.__name__, fn.__defaults__,
            tuple(cellmap[n] for n in made.__code__.co_freevars))
        out.__kwdefaults__ = fn.__kwdefaults__
    else:
        code = compile(tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        ns = {}
        exec(code, glb, ns)
        out = ns[fdef.name]
    out = functools.wraps(fn)(out)
    out.__dy2static__ = True
    out.__converted_source__ = ast.unparse(tree)
    return out


def _merge_tail_returns(body):
    """Rewrite `if c: return a` followed by trailing statements into an
    if/else with the remainder as the else branch (ReturnTransformer
    analog for the most common early-return shape); recursive, so chains
    of early returns fold into nested if/else."""
    for i, s in enumerate(body):
        if (isinstance(s, ast.If) and not s.orelse
                and s.body and isinstance(s.body[-1], ast.Return)
                and not _has_shallow(s.body[:-1], ast.Return)):
            rest = _merge_tail_returns(body[i + 1:])
            if not rest or not _has_shallow(rest, ast.Return):
                break
            merged = ast.If(test=s.test, body=s.body, orelse=rest)
            ast.copy_location(merged, s)
            return body[:i] + [merged]
    return body
