"""paddle.metric — Accuracy/Precision/Recall/Auc.

Reference parity: python/paddle/metric/metrics.py + metric ops
(operators/metrics/accuracy_op.cc, auc_op.cc).
"""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor, unwrap
from .. import tensor_ops as T


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = np.asarray(unwrap(pred))
        label_np = np.asarray(unwrap(label))
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(unwrap(correct))
        accs = []
        for k in self.topk:
            num = c[..., :k].sum()
            accs.append(num / max(c.shape[0], 1))
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += c.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds)).round().astype(np.int32).ravel()
        l = np.asarray(unwrap(labels)).astype(np.int32).ravel()
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds)).round().astype(np.int32).ravel()
        l = np.asarray(unwrap(labels)).astype(np.int32).ravel()
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args,
                 **kwargs):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds))
        if p.ndim == 2:
            p = p[:, 1]
        l = np.asarray(unwrap(labels)).ravel()
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins.ravel(), l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """functional accuracy (metrics/accuracy_op.cc)."""
    import jax.numpy as jnp

    from ..tensor import apply

    def f(p, l):
        topk_idx = jnp.argsort(-p, axis=-1)[..., :k]
        ll = l if l.ndim == p.ndim - 1 else jnp.squeeze(l, -1)
        c = jnp.any(topk_idx == ll[..., None], axis=-1)
        return jnp.mean(c.astype(jnp.float32))

    return apply(f, input, label)


class PrecisionRecall(Metric):
    """Streaming multi-class precision/recall/F1
    (operators/metrics/precision_recall_op.cc): per-class TP/FP/FN from
    argmax predictions, macro + micro averages."""

    def __init__(self, num_classes, name="precision_recall"):
        self._name = name
        self.num_classes = num_classes
        self.reset()

    def reset(self):
        self._tp = np.zeros(self.num_classes, np.int64)
        self._fp = np.zeros(self.num_classes, np.int64)
        self._fn = np.zeros(self.num_classes, np.int64)

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds))
        if p.ndim == 2:
            p = p.argmax(-1)
        p = p.ravel().astype(np.int64)
        y = np.asarray(unwrap(labels)).ravel().astype(np.int64)
        C = self.num_classes
        # one O(N) confusion-matrix pass instead of 3 scans per class;
        # ids outside [0, C) don't alias into the matrix: an out-of-range
        # prediction still counts as FN for its (valid) label class, and
        # an out-of-range label as FP for its (valid) prediction class
        vp = (p >= 0) & (p < C)
        vy = (y >= 0) & (y < C)
        both = vp & vy
        conf = np.bincount(y[both] * C + p[both],
                           minlength=C * C).reshape(C, C)
        tp = np.diag(conf)
        self._tp += tp
        self._fp += conf.sum(0) - tp   # predicted c, label != c
        self._fn += conf.sum(1) - tp   # label c, predicted != c
        if not both.all():
            self._fn += np.bincount(y[vy & ~vp], minlength=C)
            self._fp += np.bincount(p[vp & ~vy], minlength=C)

    @staticmethod
    def _prf(tp, fp, fn):
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return prec, rec, f1

    def accumulate(self):
        """Returns (macro_p, macro_r, macro_f1, micro_p, micro_r,
        micro_f1) — the reference op's six accumulated outputs."""
        per = [self._prf(int(t), int(f), int(n))
               for t, f, n in zip(self._tp, self._fp, self._fn)]
        macro = tuple(float(np.mean([x[i] for x in per]))
                      for i in range(3))
        micro = self._prf(int(self._tp.sum()), int(self._fp.sum()),
                          int(self._fn.sum()))
        return macro + tuple(float(x) for x in micro)


def mean_iou(input, label, num_classes):
    """Mean intersection-over-union over classes
    (operators/metrics/mean_iou_op.h): returns (miou, per-class iou,
    present-class mask)."""
    import jax.numpy as jnp

    from ..tensor import apply

    def f(p, y):
        p = p.reshape(-1).astype(jnp.int32)
        y = y.reshape(-1).astype(jnp.int32)
        inter = jnp.zeros((num_classes,), jnp.float32).at[p].add(
            (p == y).astype(jnp.float32))
        pred_c = jnp.zeros((num_classes,), jnp.float32).at[p].add(1.0)
        lab_c = jnp.zeros((num_classes,), jnp.float32).at[y].add(1.0)
        union = pred_c + lab_c - inter
        present = union > 0
        iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
        miou = iou.sum() / jnp.maximum(present.sum(), 1)
        return miou, iou, present

    return apply(f, input, label, _multi_out=True)


def edit_distance(hyps, hyp_lens, refs, ref_lens, normalized=True):
    """Batch Levenshtein distance (operators/edit_distance_op.h): padded
    id arrays + lens; host-side DP like the reference CPU kernel.
    Returns (distances [B,1], sequence_num)."""
    h = np.asarray(unwrap(hyps))
    r = np.asarray(unwrap(refs))
    hl = np.asarray(unwrap(hyp_lens)).ravel().astype(int)
    rl = np.asarray(unwrap(ref_lens)).ravel().astype(int)
    out = np.zeros((len(hl), 1), np.float32)
    for b in range(len(hl)):
        a, bseq = h[b, :hl[b]], r[b, :rl[b]]
        n, m = len(a), len(bseq)
        d = np.arange(m + 1, dtype=np.int64)
        for i in range(1, n + 1):
            prev, d[0] = d[0], i
            for j in range(1, m + 1):
                cur = min(d[j] + 1, d[j - 1] + 1,
                          prev + (a[i - 1] != bseq[j - 1]))
                prev, d[j] = d[j], cur
        dist = float(d[m])
        out[b, 0] = dist / m if (normalized and m) else dist
    from ..tensor import Tensor
    return Tensor(out), len(hl)


class ChunkEvaluator(Metric):
    """Chunking F1 for IOB / IOE / IOBES / plain tagging
    (operators/metrics/chunk_eval_op.h re-designed host-side): update
    with padded tag ids + lens, accumulate (precision, recall, f1).

    Numeric tag scheme (the reference's): tag = chunk_type * num_tags +
    tag_role with num_tags = 2 for IOB (roles B,I) and IOE (roles I,E),
    4 for IOBES (roles B,I,E,S), 1 for plain; any tag >=
    num_tags * num_chunk_types (typically the next id) is Outside.
    Pass num_chunk_types (or a label_list of length
    num_tags*num_chunk_types + 1); without either, every tag is a
    chunk tag."""

    # role alphabets: IO = bare per-type Inside tags (maximal same-type
    # runs form one chunk); PLAIN = every tagged token its own chunk
    _ROLES = {"IOB": "BI", "IOE": "IE", "IOBES": "BIES", "IO": "I",
              "PLAIN": "S"}

    def __init__(self, label_list=None, scheme="IOB", name="chunk",
                 num_chunk_types=None, excluded_chunk_types=()):
        scheme = scheme.upper()
        if scheme not in self._ROLES:
            raise ValueError(
                f"chunk scheme {scheme!r}: one of IOB/IOE/IOBES/IO/plain")
        self._name = name
        self.label_list = label_list
        self.scheme = scheme
        self._ntags = len(self._ROLES[scheme])
        if num_chunk_types is None and label_list is not None:
            num_chunk_types = (len(label_list) - 1) // self._ntags
        self.num_chunk_types = num_chunk_types
        self.excluded = set(excluded_chunk_types)
        self.reset()

    def reset(self):
        self._correct = self._infer = self._label = 0

    def _decode(self, t):
        """tag id -> (chunk_type, role) or None for Outside."""
        if t < 0 or (self.num_chunk_types is not None
                     and t >= self._ntags * self.num_chunk_types):
            return None
        return t // self._ntags, self._ROLES[self.scheme][t % self._ntags]

    def _chunks(self, tags):
        """(type, start, end) chunks, conlleval-style begin/end rules:
        B/S (and a role that does not continue the open chunk) begin;
        E/S end; I continues."""
        chunks, start, ctype = [], None, None

        def flush(end):
            nonlocal start, ctype
            if start is not None and ctype not in self.excluded:
                chunks.append((ctype, start, end))
            start = ctype = None

        for i, t in enumerate(tags):
            d = self._decode(int(t))
            if d is None:
                flush(i)
                continue
            ty, role = d
            continues = (start is not None and ty == ctype
                         and role in ("I", "E"))
            if not continues:
                flush(i)
                start, ctype = i, ty
            if role in ("E", "S"):
                flush(i + 1)
        flush(len(tags))
        return set(chunks)

    def update(self, inferences, labels, seq_lens):
        inf = np.asarray(unwrap(inferences))
        lab = np.asarray(unwrap(labels))
        lens = np.asarray(unwrap(seq_lens)).ravel().astype(int)
        for b, n in enumerate(lens):
            ci = self._chunks(inf[b, :n])
            cl = self._chunks(lab[b, :n])
            self._correct += len(ci & cl)
            self._infer += len(ci)
            self._label += len(cl)

    def accumulate(self):
        p = self._correct / self._infer if self._infer else 0.0
        r = self._correct / self._label if self._label else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        return p, r, f1


class DetectionMAP(Metric):
    """VOC-style detection mAP (operators/detection_map_op.h, 11-point or
    integral): update with per-image detections and ground truth."""

    def __init__(self, overlap_threshold=0.5, ap_version="integral",
                 name="mAP"):
        self._name = name
        self.thresh = overlap_threshold
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._dets = {}   # class -> list of (score, is_tp)
        self._npos = {}   # class -> gt count

    @staticmethod
    def _iou(a, b):
        from ..vision.ops import _pairwise_iou_np

        return float(_pairwise_iou_np(a[None], b[None])[0, 0])

    def update(self, det_boxes, det_scores, det_labels, gt_boxes,
               gt_labels):
        """One image: detections [N,4]/[N]/[N] + ground truth [M,4]/[M]."""
        db = np.asarray(unwrap(det_boxes), np.float64).reshape(-1, 4)
        ds = np.asarray(unwrap(det_scores), np.float64).ravel()
        dl = np.asarray(unwrap(det_labels)).ravel().astype(int)
        gb = np.asarray(unwrap(gt_boxes), np.float64).reshape(-1, 4)
        gl = np.asarray(unwrap(gt_labels)).ravel().astype(int)
        for c in np.unique(gl):
            self._npos[int(c)] = self._npos.get(int(c), 0) + int(
                (gl == c).sum())
        for c in np.unique(dl):
            c = int(c)
            idx = np.where(dl == c)[0][np.argsort(-ds[dl == c])]
            taken = np.zeros(len(gb), bool)
            for i in idx:
                best, bj = 0.0, -1
                for j in np.where(gl == c)[0]:
                    v = self._iou(db[i], gb[j])
                    if v > best:
                        best, bj = v, j
                tp = best >= self.thresh and bj >= 0 and not taken[bj]
                if tp:
                    taken[bj] = True
                self._dets.setdefault(c, []).append((float(ds[i]), tp))

    def accumulate(self):
        aps = []
        for c, npos in self._npos.items():
            dets = sorted(self._dets.get(c, []), reverse=True)
            if not dets or npos == 0:
                aps.append(0.0)
                continue
            tp = np.cumsum([d[1] for d in dets])
            fp = np.cumsum([not d[1] for d in dets])
            rec = tp / npos
            prec = tp / np.maximum(tp + fp, 1e-12)
            if self.ap_version == "11point":
                ap = float(np.mean([
                    prec[rec >= t].max() if (rec >= t).any() else 0.0
                    for t in np.linspace(0, 1, 11)]))
            else:
                mrec = np.concatenate([[0], rec, [1]])
                mpre = np.concatenate([[0], prec, [0]])
                for i in range(len(mpre) - 2, -1, -1):
                    mpre[i] = max(mpre[i], mpre[i + 1])
                idx = np.where(mrec[1:] != mrec[:-1])[0]
                ap = float(((mrec[idx + 1] - mrec[idx])
                            * mpre[idx + 1]).sum())
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0

from . import metrics  # noqa: E402,F401 — ref metric/__init__.py submodule
