"""paddle.metric.metrics — the module the reference re-exports classes
from (python/paddle/metric/__init__.py: `from .metrics import ...`);
aliased to the package surface here."""
from . import (  # noqa: F401
    Accuracy, Auc, Metric, Precision, Recall)

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]
