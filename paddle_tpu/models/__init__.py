"""Model zoo: language models (GPT/BERT/ERNIE-style) + hybrid-parallel GPT.

The reference ships vision models only (python/paddle/vision/models); its
language workloads (BERT/ERNIE/GPT-3 in BASELINE.md) live in external repos.
Here they are first-class: these are the flagship models the benchmarks and
the multi-chip dryrun drive.
"""
from .gpt import GPTConfig, GPTModel, GPTForCausalLM  # noqa: F401
from .bert import BertConfig, BertModel, BertForPretraining, ErnieModel  # noqa: F401
from .interop import load_hf_bert, load_hf_gpt2  # noqa: F401
from . import gpt_hybrid  # noqa: F401
