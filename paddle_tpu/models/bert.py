"""BERT/ERNIE-style bidirectional encoder + pretraining heads.

Workload parity: BASELINE.md configs 3 (BERT-base Fleet) and 4 (ERNIE AMP).
Built on the same nn.TransformerEncoder the reference exposes
(python/paddle/nn/layer/transformer.py:404,541); ERNIE shares the
architecture (segment embeddings + MLM/NSP heads), so `ErnieModel` is the
same graph with ERNIE defaults.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import tensor_ops as T
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer, ParamAttr
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer
from ..ops import fused


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02


def _init(cfg):
    return ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range))


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word = Embedding(cfg.vocab_size, cfg.hidden_size,
                              weight_attr=_init(cfg))
        self.position = Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                                  weight_attr=_init(cfg))
        self.token_type = Embedding(cfg.type_vocab_size, cfg.hidden_size,
                                    weight_attr=_init(cfg))
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_epsilon)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None):
        import paddle_tpu as paddle

        pos = paddle.arange(input_ids.shape[1])
        x = self.word(input_ids) + self.position(pos)
        if token_type_ids is None:
            # BERT semantics: absent segment ids mean segment 0 — the
            # type-0 embedding row is still ADDED (HF/paddlenlp default
            # token_type_ids=zeros), not skipped; skipping shifts every
            # hidden state and breaks checkpoint parity
            x = x + self.token_type.weight[0]
        else:
            x = x + self.token_type(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertModel(Layer):
    def __init__(self, cfg: BertConfig | None = None, **kwargs):
        super().__init__()
        self.cfg = cfg or BertConfig(**kwargs)
        cfg = self.cfg
        self.embeddings = BertEmbeddings(cfg)
        layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.dropout, activation="gelu")
        self.encoder = TransformerEncoder(layer, cfg.num_layers)
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size,
                             weight_attr=_init(cfg))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        seq = self.encoder(x, src_mask=attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForPretraining(Layer):
    """MLM + NSP heads (the BERT-base pretraining objective)."""

    def __init__(self, cfg: BertConfig | None = None, **kwargs):
        super().__init__()
        self.bert = BertModel(cfg, **kwargs)
        cfg = self.bert.cfg
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    weight_attr=_init(cfg))
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_epsilon)
        self.mlm_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.nsp = Linear(cfg.hidden_size, 2, weight_attr=_init(cfg))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        # decoder tied to the word embedding (BERT weight tying)
        logits = T.matmul(
            h, T.transpose(self.bert.embeddings.word.weight, [1, 0]))
        logits = logits + self.mlm_bias
        return logits, self.nsp(pooled)

    def loss(self, input_ids, mlm_labels, nsp_labels, token_type_ids=None,
             ignore_index=-100):
        mlm_logits, nsp_logits = self.forward(input_ids, token_type_ids)
        mlm = fused.softmax_cross_entropy(mlm_logits, mlm_labels,
                                          ignore_index=ignore_index)
        denom = T.cast(T.sum(T.cast(mlm_labels != ignore_index, "float32")),
                       "float32")
        mlm_loss = T.sum(mlm) / T.clip(denom, min=1.0)
        nsp_loss = T.mean(fused.softmax_cross_entropy(nsp_logits, nsp_labels))
        return mlm_loss + nsp_loss


class ErnieModel(BertModel):
    """ERNIE 1.0/2.0 share BERT's graph with different defaults + data
    (entity masking lives in the data pipeline, not the model)."""

    def __init__(self, cfg: BertConfig | None = None, **kwargs):
        if cfg is None:
            defaults = dict(vocab_size=18000, type_vocab_size=4)
            defaults.update(kwargs)
            cfg = BertConfig(**defaults)
        super().__init__(cfg)
