"""GPT decoder-only language model, tensor-parallel-ready.

Workload parity: BASELINE.md config 5 (GPT-3 1.3B with TP+PP).  The reference
tree has no GPT implementation (it lives in PaddleNLP); this is the TPU-native
flagship: GSPMD tensor parallelism via the meta_parallel layers (weights carry
PartitionSpecs; XLA inserts the Megatron collectives), optional
sequence-parallel ring attention for long context, fused attention via the
Pallas flash kernel on TPU (ops/fused.scaled_dot_product_attention).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import tensor_ops as T
from ..distributed.meta_parallel import (ColumnParallelLinear,
                                         RowParallelLinear,
                                         VocabParallelEmbedding,
                                         shard_constraint)
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer, ParamAttr
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.norm import LayerNorm
from ..ops import fused
from ..tensor import Tensor


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden_size: int | None = None  # default 4*hidden
    max_position_embeddings: int = 1024
    dropout: float = 0.1
    attn_dropout: float = 0.1
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    tensor_parallel: bool = False   # annotate weights for an `mp` mesh axis
    sequence_parallel: bool = False  # ring attention over an `sp` mesh axis
    tie_word_embeddings: bool = True
    recompute: bool = False  # remat each block (fluid RecomputeOptimizer,
                             # optimizer.py:4533) — activations between
                             # blocks are the only saved residuals

    @property
    def ffn_size(self):
        return self.ffn_hidden_size or 4 * self.hidden_size


def _init(cfg):
    return ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range))


class GPTAttention(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        H = cfg.hidden_size
        if cfg.tensor_parallel:
            self.qkv = ColumnParallelLinear(H, 3 * H, weight_attr=_init(cfg),
                                            gather_output=False)
            self.out = RowParallelLinear(H, H, weight_attr=_init(cfg),
                                         input_is_parallel=True)
        else:
            self.qkv = Linear(H, 3 * H, weight_attr=_init(cfg))
            self.out = Linear(H, H, weight_attr=_init(cfg))
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x, return_kv=False):
        cfg = self.cfg
        B, S = x.shape[0], x.shape[1]
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = self.qkv(x)
        qkv = T.reshape(qkv, [B, S, 3, nh, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.tensor_parallel:
            # heads follow the qkv column shards
            q = shard_constraint(q, None, None, "mp", None)
            k = shard_constraint(k, None, None, "mp", None)
            v = shard_constraint(v, None, None, "mp", None)
        if cfg.sequence_parallel:
            from ..ops.ring_attention import ring_attention

            ctx = ring_attention(q, k, v, causal=True)
        else:
            ctx = fused.scaled_dot_product_attention(
                q, k, v, dropout_p=cfg.attn_dropout, is_causal=True,
                training=self.training)
        ctx = T.reshape(ctx, [B, S, cfg.hidden_size])
        out = self.dropout(self.out(ctx))
        if return_kv:
            return out, k, v  # [B, S, nh, hd] — prefill seeds the KV cache
        return out

    def decode_slots(self, x, k_cache, v_cache, pos, active):
        """Continuous-batching decode: one token per cache SLOT, each at
        its OWN position (the batched generalization of decode_step for
        paddle_tpu.serving.generation — lanes belong to different
        requests admitted at different times, so there is no shared
        scalar position).

        x: [slots, 1, H] hidden; caches: [slots, S_max, nh, hd];
        pos: [slots] int32 per-lane write index; active: [slots] bool —
        inactive lanes leave their cache rows untouched.  Returns
        (out, k', v').  Per-lane math is identical to decode_step at the
        same position, which is what makes an engine lane bitwise-equal
        to a solo ``generate`` run.
        """
        import jax.numpy as jnp
        from jax import lax

        from ..tensor import unwrap

        cfg = self.cfg
        B = x.shape[0]
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = T.reshape(self.qkv(x), [B, 1, 3, nh, hd])
        q = unwrap(qkv[:, :, 0])                     # [slots, 1, nh, hd]
        k = unwrap(qkv[:, :, 1])
        v = unwrap(qkv[:, :, 2])
        pos = jnp.asarray(unwrap(pos), jnp.int32)
        active = jnp.asarray(unwrap(active), bool)
        k_cache, v_cache = unwrap(k_cache), unwrap(v_cache)
        # per-lane scatter: lane b writes column pos[b] (dynamic_update
        # _slice cannot express per-row offsets; the one-hot where is the
        # jit-safe equivalent and XLA fuses it into the cache update)
        write = (jnp.arange(k_cache.shape[1])[None, :] == pos[:, None]) \
            & active[:, None]                         # [slots, S_max]
        k_cache = jnp.where(write[:, :, None, None], k, k_cache)
        v_cache = jnp.where(write[:, :, None, None], v, v_cache)
        if cfg.tensor_parallel:
            # head-axis pinning, as in forward()/decode_step: without it
            # GSPMD may gather the cache every decode iteration
            q = unwrap(shard_constraint(Tensor(q), None, None, "mp", None))
            k_cache = unwrap(shard_constraint(
                Tensor(k_cache), None, None, "mp", None))
            v_cache = unwrap(shard_constraint(
                Tensor(v_cache), None, None, "mp", None))
        scores = jnp.einsum("bqnd,bsnd->bnqs", q, k_cache) \
            * (1.0 / float(hd) ** 0.5)
        valid = jnp.arange(k_cache.shape[1])[None, :] <= pos[:, None]
        scores = jnp.where(valid[:, None, None, :], scores,
                           jnp.finfo(scores.dtype).min)
        probs = jnp.exp(scores - lax.stop_gradient(
            scores.max(axis=-1, keepdims=True)))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        ctx = jnp.einsum("bnqs,bsnd->bqnd", probs, v_cache)
        out = self.out(Tensor(ctx.reshape(B, 1, cfg.hidden_size)))
        return out, Tensor(k_cache), Tensor(v_cache)

    def decode_pages(self, x, k_pages, v_pages, rows, pos, active,
                     seq_cap):
        """Paged continuous-batching decode: like ``decode_slots`` but
        each lane's KV lives in fixed-size pool pages indirected through
        its page-table row (serving/kv_cache.py) instead of a dense
        ``[slots, S_max]`` stripe.

        x: [slots, 1, H]; k_pages/v_pages: [num_pages, page_size, nh,
        hd] (this layer's pool plane); rows: [slots, pages_per_slot]
        int32 page table (-1 = unmapped); pos: [slots] write index;
        active: [slots]; seq_cap: STATIC attention extent (the engine's
        S_max) — the gathered view is sliced to it so the softmax
        reduction shape matches the dense path exactly, which is what
        keeps an engine lane bitwise-equal to a solo ``generate`` run.
        Unmapped (-1) table entries gather an arbitrary resident page
        whose positions sit past the validity mask, so they contribute
        exactly 0 to the softmax (exp of finfo.min underflows).
        """
        import jax.numpy as jnp
        from jax import lax

        from ..tensor import unwrap

        cfg = self.cfg
        B = x.shape[0]
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = T.reshape(self.qkv(x), [B, 1, 3, nh, hd])
        q = unwrap(qkv[:, :, 0])                     # [slots, 1, nh, hd]
        k = unwrap(qkv[:, :, 1])[:, 0]               # [slots, nh, hd]
        v = unwrap(qkv[:, :, 2])[:, 0]
        pos = jnp.asarray(unwrap(pos), jnp.int32)
        active = jnp.asarray(unwrap(active), bool)
        k_pages, v_pages = unwrap(k_pages), unwrap(v_pages)
        rows = jnp.asarray(unwrap(rows), jnp.int32)
        num_pages, ps = k_pages.shape[0], k_pages.shape[1]
        lane = jnp.arange(B)
        # per-lane scatter: lane b writes its token's K/V at
        # (rows[b, pos[b]//ps], pos[b]%ps); inactive lanes target
        # one-past-the-pool and are dropped
        page = rows[lane, jnp.clip(pos // ps, 0, rows.shape[1] - 1)]
        page = jnp.where(active, page, num_pages)
        off = pos % ps
        k_pages = k_pages.at[page, off].set(k.astype(k_pages.dtype),
                                            mode="drop")
        v_pages = v_pages.at[page, off].set(v.astype(v_pages.dtype),
                                            mode="drop")
        # hot path: the Pallas ragged kernel walks each lane's page-table
        # row and reads the pool in place — no dense [slots, seq_cap]
        # gather is materialized.  None => flag off / untileable geometry
        # (counted in paddle_pallas_fallbacks_total); the dense gather
        # below stays as the reference and fallback.
        ctx = fused.paged_decode_attention(
            q, k_pages, v_pages, rows, pos, seq_cap,
            tp_axis="mp" if cfg.tensor_parallel else None)
        if ctx is None:
            # gather each lane's pages into a contiguous [seq_cap] view
            gidx = jnp.clip(rows, 0, num_pages - 1)
            kg = k_pages[gidx].reshape(B, rows.shape[1] * ps, nh, hd)
            vg = v_pages[gidx].reshape(B, rows.shape[1] * ps, nh, hd)
            kg, vg = kg[:, :seq_cap], vg[:, :seq_cap]
            scores = jnp.einsum("bqnd,bsnd->bnqs", q, kg) \
                * (1.0 / float(hd) ** 0.5)
            valid = jnp.arange(seq_cap)[None, :] <= pos[:, None]
            scores = jnp.where(valid[:, None, None, :], scores,
                               jnp.finfo(scores.dtype).min)
            probs = jnp.exp(scores - lax.stop_gradient(
                scores.max(axis=-1, keepdims=True)))
            probs = probs / probs.sum(axis=-1, keepdims=True)
            ctx = jnp.einsum("bnqs,bsnd->bqnd", probs, vg)
        else:
            ctx = unwrap(ctx)
        out = self.out(Tensor(ctx.reshape(B, 1, cfg.hidden_size)))
        return out, Tensor(k_pages), Tensor(v_pages)

    def verify_pages(self, x, k_pages, v_pages, rows, positions, active,
                     seq_cap):
        """Speculative-decode verification attention: like
        ``decode_pages`` but each lane carries a CHUNK of C candidate
        tokens at consecutive positions instead of one — the target
        model scores every draft proposal in a single batched step.

        x: [slots, C, H]; k_pages/v_pages: [num_pages, page_size, nh,
        hd] (this layer's pool plane); rows: [slots, pages_per_slot]
        int32 page table; positions: [slots, C] absolute write index
        per candidate (consecutive per lane, clamped by the engine so
        they never run past the slot's reserved extent); active:
        [slots]; seq_cap: STATIC attention extent.  Causality inside
        the chunk falls out of the position mask: candidate i's query
        admits exactly the keys at slots <= positions[b, i], which by
        construction are the committed history plus candidates 0..i —
        the same reduction extent the non-speculative decode step would
        have seen one token at a time, which is what keeps accepted
        tokens bitwise-equal to the sequential path.
        """
        import jax.numpy as jnp
        from jax import lax

        from ..tensor import unwrap

        cfg = self.cfg
        B, C = x.shape[0], x.shape[1]
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = T.reshape(self.qkv(x), [B, C, 3, nh, hd])
        q = unwrap(qkv[:, :, 0])                     # [slots, C, nh, hd]
        k = unwrap(qkv[:, :, 1])
        v = unwrap(qkv[:, :, 2])
        positions = jnp.asarray(unwrap(positions), jnp.int32)
        active = jnp.asarray(unwrap(active), bool)
        k_pages, v_pages = unwrap(k_pages), unwrap(v_pages)
        rows = jnp.asarray(unwrap(rows), jnp.int32)
        num_pages, ps = k_pages.shape[0], k_pages.shape[1]
        lane = jnp.arange(B)
        # per-element scatter: candidate (b, i) writes its K/V at
        # (rows[b, positions[b,i]//ps], positions[b,i]%ps); inactive
        # lanes target one-past-the-pool and are dropped.  Clamped
        # duplicate positions (end-of-budget) may collide — whichever
        # write wins is garbage no emitted query's mask ever exposes.
        page = rows[lane[:, None],
                    jnp.clip(positions // ps, 0, rows.shape[1] - 1)]
        page = jnp.where(active[:, None], page, num_pages)
        off = positions % ps
        k_pages = k_pages.at[page, off].set(k.astype(k_pages.dtype),
                                            mode="drop")
        v_pages = v_pages.at[page, off].set(v.astype(v_pages.dtype),
                                            mode="drop")
        # dense per-lane gather (the decode_pages fallback math with a
        # C-wide query dim); no Pallas path — verification is one step
        # per K drafted tokens, off the per-token hot loop
        gidx = jnp.clip(rows, 0, num_pages - 1)
        kg = k_pages[gidx].reshape(B, rows.shape[1] * ps, nh, hd)
        vg = v_pages[gidx].reshape(B, rows.shape[1] * ps, nh, hd)
        kg, vg = kg[:, :seq_cap], vg[:, :seq_cap]
        scores = jnp.einsum("bqnd,bsnd->bnqs", q, kg) \
            * (1.0 / float(hd) ** 0.5)
        valid = jnp.arange(seq_cap)[None, None, :] <= positions[:, :, None]
        scores = jnp.where(valid[:, None], scores,
                           jnp.finfo(scores.dtype).min)
        probs = jnp.exp(scores - lax.stop_gradient(
            scores.max(axis=-1, keepdims=True)))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        ctx = jnp.einsum("bnqs,bsnd->bqnd", probs, vg)
        out = self.out(Tensor(ctx.reshape(B, C, cfg.hidden_size)))
        return out, Tensor(k_pages), Tensor(v_pages)

    def prefill_prefix(self, x, prefix_k, prefix_v, prefix_len):
        """Suffix-only prefill attending over a cached prefix: queries
        are the suffix tokens (absolute positions ``prefix_len + i``),
        keys are [prefix ++ suffix] with the prefix entries valid below
        ``prefix_len`` and the suffix causal — the attention that lets a
        prefix-cache hit skip recomputing the shared pages entirely.

        x: [1, Ss, H] suffix hidden; prefix_k/prefix_v: [C, nh, hd]
        gathered prefix K/V (C static, entries >= prefix_len garbage);
        prefix_len: traced scalar.  Returns (out, k_suf, v_suf) with
        k_suf/v_suf [1, Ss, nh, hd] — the engine pages them in at the
        (page-aligned) prefix boundary.
        """
        import jax.numpy as jnp
        from jax import lax

        from ..tensor import unwrap

        cfg = self.cfg
        S = x.shape[1]
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = T.reshape(self.qkv(x), [1, S, 3, nh, hd])
        q = unwrap(qkv[:, :, 0])                     # [1, Ss, nh, hd]
        k = unwrap(qkv[:, :, 1])
        v = unwrap(qkv[:, :, 2])
        prefix_len = jnp.asarray(unwrap(prefix_len), jnp.int32)
        pk = jnp.asarray(unwrap(prefix_k))[None]     # [1, C, nh, hd]
        pv = jnp.asarray(unwrap(prefix_v))[None]
        C = pk.shape[1]
        kk = jnp.concatenate([pk.astype(k.dtype), k], axis=1)
        vv = jnp.concatenate([pv.astype(v.dtype), v], axis=1)
        scores = jnp.einsum("bqnd,bsnd->bnqs", q, kk) \
            * (1.0 / float(hd) ** 0.5)
        i = jnp.arange(S)[:, None]
        j = jnp.arange(C + S)[None, :]
        ok = (j < prefix_len) | ((j >= C) & (j - C <= i))
        scores = jnp.where(ok[None, None], scores,
                           jnp.finfo(scores.dtype).min)
        probs = jnp.exp(scores - lax.stop_gradient(
            scores.max(axis=-1, keepdims=True)))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        ctx = jnp.einsum("bnqs,bsnd->bqnd", probs, vv)
        out = self.dropout(self.out(Tensor(
            ctx.reshape(1, S, cfg.hidden_size))))
        return out, Tensor(k), Tensor(v)

    def decode_step(self, x, k_cache, v_cache, pos):
        """One-token cached attention (the KV-cache serving path; the
        reference's analog is fused_multi_transformer's CacheKV decode,
        operators/fused/ — here it is lax-level dynamic_update_slice +
        masked attention over the static cache, jit/scan-safe).

        x: [B, 1, H] hidden; caches: [B, S_max, nh, hd]; pos: scalar int32
        index of the slot this token occupies.  Returns (out, k', v').
        """
        import jax.numpy as jnp
        from jax import lax

        from ..tensor import unwrap

        cfg = self.cfg
        B = x.shape[0]
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = T.reshape(self.qkv(x), [B, 1, 3, nh, hd])
        q = unwrap(qkv[:, :, 0])                     # [B, 1, nh, hd]
        k = unwrap(qkv[:, :, 1])
        v = unwrap(qkv[:, :, 2])
        pos = jnp.asarray(unwrap(pos), jnp.int32)
        zero = jnp.int32(0)
        k_cache = lax.dynamic_update_slice(
            unwrap(k_cache), k, (zero, pos, zero, zero))
        v_cache = lax.dynamic_update_slice(
            unwrap(v_cache), v, (zero, pos, zero, zero))
        if cfg.tensor_parallel:
            # same head-axis pinning as forward(): without it GSPMD may
            # pick a gathered layout for the per-step attention and pay
            # an all-gather every decode step
            q = unwrap(shard_constraint(Tensor(q), None, None, "mp", None))
            k_cache = unwrap(shard_constraint(
                Tensor(k_cache), None, None, "mp", None))
            v_cache = unwrap(shard_constraint(
                Tensor(v_cache), None, None, "mp", None))
        # masked attention over the whole static cache: slots past `pos`
        # are -inf so the softmax ignores unwritten entries
        scores = jnp.einsum("bqnd,bsnd->bnqs", q, k_cache) \
            * (1.0 / float(hd) ** 0.5)
        valid = jnp.arange(k_cache.shape[1]) <= pos   # [S_max]
        scores = jnp.where(valid[None, None, None, :], scores,
                           jnp.finfo(scores.dtype).min)
        probs = jnp.exp(scores - lax.stop_gradient(
            scores.max(axis=-1, keepdims=True)))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        ctx = jnp.einsum("bnqs,bsnd->bqnd", probs, v_cache)
        out = self.out(Tensor(ctx.reshape(B, 1, cfg.hidden_size)))
        return out, Tensor(k_cache), Tensor(v_cache)


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        H, FF = cfg.hidden_size, cfg.ffn_size
        if cfg.tensor_parallel:
            self.fc1 = ColumnParallelLinear(H, FF, weight_attr=_init(cfg),
                                            gather_output=False)
            self.fc2 = RowParallelLinear(FF, H, weight_attr=_init(cfg),
                                         input_is_parallel=True)
        else:
            self.fc1 = Linear(H, FF, weight_attr=_init(cfg))
            self.fc2 = Linear(FF, H, weight_attr=_init(cfg))
        self._tp = cfg.tensor_parallel
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x):
        # expansion matmul with fused bias+GeLU epilogue (exact erf, same
        # as F.gelu's default) instead of fc1 -> separate gelu
        h = fused.linear_bias_gelu(x, self.fc1.weight, self.fc1.bias)
        if self._tp:
            # re-pin the column shards fc1.forward would have pinned
            h = shard_constraint(h, *([None] * (len(h.shape) - 1) + ["mp"]))
        return self.dropout(self.fc2(h))


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.attn = GPTAttention(cfg)
        self.ln_2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.mlp = GPTMLP(cfg)

    def forward(self, x, return_kv=False):
        if return_kv:
            a, k, v = self.attn(self.ln_1(x), return_kv=True)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, k, v
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x

    def decode_step(self, x, k_cache, v_cache, pos):
        a, k_cache, v_cache = self.attn.decode_step(
            self.ln_1(x), k_cache, v_cache, pos)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_cache, v_cache

    def decode_slots(self, x, k_cache, v_cache, pos, active):
        a, k_cache, v_cache = self.attn.decode_slots(
            self.ln_1(x), k_cache, v_cache, pos, active)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_cache, v_cache

    def decode_pages(self, x, k_pages, v_pages, rows, pos, active,
                     seq_cap):
        a, k_pages, v_pages = self.attn.decode_pages(
            self.ln_1(x), k_pages, v_pages, rows, pos, active, seq_cap)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_pages, v_pages

    def verify_pages(self, x, k_pages, v_pages, rows, positions, active,
                     seq_cap):
        a, k_pages, v_pages = self.attn.verify_pages(
            self.ln_1(x), k_pages, v_pages, rows, positions, active,
            seq_cap)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_pages, v_pages

    def prefill_prefix(self, x, prefix_k, prefix_v, prefix_len):
        a, k, v = self.attn.prefill_prefix(
            self.ln_1(x), prefix_k, prefix_v, prefix_len)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k, v


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tensor_parallel:
            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                              weight_attr=_init(cfg))
        else:
            self.wte = Embedding(cfg.vocab_size, cfg.hidden_size,
                                 weight_attr=_init(cfg))
        self.wpe = Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                             weight_attr=_init(cfg))
        self.drop = Dropout(cfg.dropout)
        self.h = [GPTBlock(cfg) for _ in range(cfg.num_layers)]
        for i, blk in enumerate(self.h):
            self.add_sublayer(f"h_{i}", blk)
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)

    def forward(self, input_ids):
        import paddle_tpu as paddle

        pos = paddle.arange(input_ids.shape[1])
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if self.cfg.recompute:
            from ..distributed.recompute import recompute as _remat
            for blk in self.h:
                x = _remat(blk, x)
        else:
            for blk in self.h:
                x = blk(x)
        return self.ln_f(x)

    def prefill(self, input_ids, cache_len):
        """Batched prompt pass seeding per-layer KV caches of static
        length ``cache_len`` (>= prompt + new tokens).  Returns
        (hidden [B,S,H], caches: tuple of (k,v) [B,cache_len,nh,hd])."""
        import jax.numpy as jnp

        import paddle_tpu as paddle

        from ..tensor import unwrap

        cfg = self.cfg
        if self.training:
            raise RuntimeError(
                "prefill/decode_step are eval-only serving paths (the "
                "decode half applies no dropout, so a training-mode "
                "prefill would be statistically inconsistent with it); "
                "call model.eval() first")
        B, S = input_ids.shape[0], input_ids.shape[1]
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        pos = paddle.arange(S)
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        caches = []
        for blk in self.h:
            x, k, v = blk(x, return_kv=True)
            kc = jnp.zeros((B, cache_len, nh, hd),
                           unwrap(k).dtype).at[:, :S].set(unwrap(k))
            vc = jnp.zeros((B, cache_len, nh, hd),
                           unwrap(v).dtype).at[:, :S].set(unwrap(v))
            caches.append((kc, vc))
        return self.ln_f(x), tuple(caches)

    def decode_step(self, token_ids, pos, caches):
        """One decode step: token_ids [B,1] at absolute position ``pos``
        (scalar); caches as returned by prefill.  Returns (hidden [B,1,H],
        new caches)."""
        from ..tensor import unwrap

        x = self.wte(token_ids) + self.wpe(T.reshape(Tensor(pos), [1]))
        new_caches = []
        for blk, (kc, vc) in zip(self.h, caches):
            x, kc, vc = blk.decode_step(x, kc, vc, pos)
            new_caches.append((unwrap(kc), unwrap(vc)))
        return self.ln_f(x), tuple(new_caches)

    def decode_slots(self, token_ids, pos, caches, active):
        """Continuous-batching decode step: token_ids [slots,1], each
        lane at its own absolute position ``pos[slot]``; ``active``
        masks lanes whose slot currently holds no request.  Returns
        (hidden [slots,1,H], new caches)."""
        from ..tensor import unwrap

        x = self.wte(token_ids) \
            + self.wpe(T.reshape(Tensor(unwrap(pos)), [-1, 1]))
        new_caches = []
        for blk, (kc, vc) in zip(self.h, caches):
            x, kc, vc = blk.decode_slots(x, kc, vc, pos, active)
            new_caches.append((unwrap(kc), unwrap(vc)))
        return self.ln_f(x), tuple(new_caches)


class GPTForCausalLM(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                  weight_attr=_init(cfg), bias_attr=False)

    def forward(self, input_ids, labels=None):
        hidden = self.gpt(input_ids)
        logits = self._head(hidden)
        if labels is None:
            return logits
        loss = fused.softmax_cross_entropy(
            logits[:, :-1], labels[:, 1:])
        return logits, T.mean(loss)

    def loss(self, input_ids):
        """Next-token LM loss on a batch of token ids, via the chunked
        fused LM-head matmul + cross entropy (ops/fused.py
        fused_linear_cross_entropy) — the fp32 [B*S, V] logits never
        materialize in HBM at once."""
        hidden = self.gpt(input_ids)
        if self.cfg.tie_word_embeddings:
            w = T.transpose(self.gpt.wte.weight, [1, 0])
        else:
            w = self.lm_head.weight
        loss = fused.fused_linear_cross_entropy(
            hidden[:, :-1], w, input_ids[:, 1:])
        return T.mean(loss)

    def _head(self, hidden):
        if self.cfg.tie_word_embeddings:
            return T.matmul(hidden,
                            T.transpose(self.gpt.wte.weight, [1, 0]))
        return self.lm_head(hidden)

    def slot_prefill(self, input_ids, length):
        """Serving prefill for ONE request (paddle_tpu.serving.generation):
        input_ids [1, Sp] right-padded to the prompt bucket ``Sp``,
        ``length`` the real prompt length L (traced int32).  Causal
        attention makes the padded tail invisible to positions < L, so
        the returned last-real-token logits are exact; the padded tail's
        K/V entries are garbage the engine's per-slot position mask never
        exposes (and overwrites as decoding advances).

        Returns (k [layers, Sp, nh, hd], v [layers, Sp, nh, hd],
        logits [V] at position L-1) as raw jax arrays — the engine
        scatters them into its device-resident slot cache.
        """
        import jax.numpy as jnp
        from jax import lax

        import paddle_tpu as paddle

        from ..tensor import unwrap

        if self.training:
            raise RuntimeError(
                "slot_prefill/slot_decode are eval-only serving paths; "
                "call model.eval() first")
        gpt = self.gpt
        S = input_ids.shape[1]
        pos = paddle.arange(S)
        x = gpt.drop(gpt.wte(input_ids) + gpt.wpe(pos))
        ks, vs = [], []
        for blk in gpt.h:
            x, k, v = blk(x, return_kv=True)
            ks.append(unwrap(k)[0])
            vs.append(unwrap(v)[0])
        hidden = gpt.ln_f(x)                         # [1, Sp, H]
        length = jnp.asarray(unwrap(length), jnp.int32)
        last = lax.dynamic_slice_in_dim(unwrap(hidden), length - 1, 1,
                                        axis=1)      # [1, 1, H]
        logits = self._head(Tensor(last))
        return jnp.stack(ks), jnp.stack(vs), unwrap(logits)[0, 0]

    def slot_decode(self, tokens, pos, active, k_cache, v_cache):
        """Serving decode iteration over the slot-batched KV cache:
        tokens [slots] int32 (each lane's pending token), pos [slots]
        int32 write positions, active [slots] bool, caches
        [layers, slots, S_max, nh, hd].  Returns (logits [slots, V],
        k_cache', v_cache') — ONE fixed-shape program regardless of
        which lanes are live (continuous batching's iteration step).
        """
        import jax.numpy as jnp

        from ..tensor import unwrap

        if self.training:
            raise RuntimeError(
                "slot_prefill/slot_decode are eval-only serving paths; "
                "call model.eval() first")
        tokens = jnp.asarray(unwrap(tokens), jnp.int32)
        k_cache, v_cache = unwrap(k_cache), unwrap(v_cache)
        caches = tuple((k_cache[i], v_cache[i])
                       for i in range(self.cfg.num_layers))
        hidden, new_caches = self.gpt.decode_slots(
            Tensor(tokens[:, None]), pos, caches, active)
        logits = self._head(hidden)                  # [slots, 1, V]
        k2 = jnp.stack([k for k, _ in new_caches])
        v2 = jnp.stack([v for _, v in new_caches])
        return unwrap(logits)[:, 0], k2, v2

    def slot_decode_paged(self, tokens, pos, active, k_pages, v_pages,
                          rows, seq_cap):
        """Serving decode iteration over the PAGED slot-batched KV cache
        (serving/kv_cache.py): tokens [slots] int32, pos [slots] write
        positions, active [slots] bool, pools [layers, num_pages,
        page_size, nh, hd], rows [slots, pages_per_slot] int32 page
        table, seq_cap the static attention extent (engine S_max).
        Returns (logits [slots, V], k_pages', v_pages') — ONE
        fixed-shape program regardless of which lanes are live or how
        pages are scattered through the pool.
        """
        import jax.numpy as jnp

        from ..tensor import unwrap

        if self.training:
            raise RuntimeError(
                "slot_prefill/slot_decode are eval-only serving paths; "
                "call model.eval() first")
        gpt = self.gpt
        tokens = jnp.asarray(unwrap(tokens), jnp.int32)
        k_pages, v_pages = unwrap(k_pages), unwrap(v_pages)
        x = gpt.wte(Tensor(tokens[:, None])) \
            + gpt.wpe(T.reshape(Tensor(unwrap(pos)), [-1, 1]))
        ks, vs = [], []
        for i, blk in enumerate(gpt.h):
            x, kp, vp = blk.decode_pages(x, k_pages[i], v_pages[i], rows,
                                         pos, active, seq_cap)
            ks.append(unwrap(kp))
            vs.append(unwrap(vp))
        logits = self._head(gpt.ln_f(x))             # [slots, 1, V]
        return unwrap(logits)[:, 0], jnp.stack(ks), jnp.stack(vs)

    def slot_verify_paged(self, tokens, positions, active, k_pages,
                          v_pages, rows, seq_cap):
        """Speculative-decode target verification over the PAGED cache:
        score a chunk of C candidate tokens per lane in ONE model step.
        tokens [slots, C] int32 (committed token ++ draft proposals),
        positions [slots, C] int32 absolute write indices (consecutive
        per lane), active [slots] bool, pools [layers, num_pages,
        page_size, nh, hd], rows [slots, pages_per_slot] int32.
        Returns (logits [slots, C, V], k_pages', v_pages') — the engine
        compares argmax(logits[:, i]) against draft proposal i+1 to
        accept or cut the speculation run.
        """
        import jax.numpy as jnp

        from ..tensor import unwrap

        if self.training:
            raise RuntimeError(
                "slot_prefill/slot_decode are eval-only serving paths; "
                "call model.eval() first")
        gpt = self.gpt
        cfg = self.cfg
        tokens = jnp.asarray(unwrap(tokens), jnp.int32)
        positions = jnp.asarray(unwrap(positions), jnp.int32)
        k_pages, v_pages = unwrap(k_pages), unwrap(v_pages)
        # clamped tail positions may sit at the extent edge; clip into
        # the embedding table (garbage rows the emission mask never
        # turns into output tokens)
        pos_emb = jnp.clip(positions, 0, cfg.max_position_embeddings - 1)
        x = gpt.wte(Tensor(tokens)) + gpt.wpe(Tensor(pos_emb))
        ks, vs = [], []
        for i, blk in enumerate(gpt.h):
            x, kp, vp = blk.verify_pages(x, k_pages[i], v_pages[i], rows,
                                         positions, active, seq_cap)
            ks.append(unwrap(kp))
            vs.append(unwrap(vp))
        logits = self._head(gpt.ln_f(x))             # [slots, C, V]
        return unwrap(logits), jnp.stack(ks), jnp.stack(vs)

    def slot_prefill_prefix(self, input_ids, prefix_k, prefix_v,
                            prefix_len, length):
        """Prefix-cache-hit prefill: run ONLY the prompt's suffix
        through the model, attending over the cached prefix K/V — the
        shared pages are never recomputed.

        input_ids [1, Ss]: suffix tokens (positions ``prefix_len ..``)
        right-padded to the suffix bucket; prefix_k/prefix_v
        [layers, C, nh, hd]: prefix K/V gathered from the page pool
        (entries >= prefix_len are garbage the mask hides);
        ``prefix_len`` (traced) the shared-prefix length, ``length`` the
        FULL prompt length.  Returns (k_suf [layers, Ss, nh, hd], v_suf,
        logits [V] at suffix index length - prefix_len - 1).  Token-
        (not bitwise-) equivalent to the full ``slot_prefill`` path:
        the math matches up to float reassociation of the explicit
        softmax vs the fused causal kernel.
        """
        import jax.numpy as jnp
        from jax import lax

        from ..tensor import unwrap

        if self.training:
            raise RuntimeError(
                "slot_prefill/slot_decode are eval-only serving paths; "
                "call model.eval() first")
        gpt = self.gpt
        cfg = self.cfg
        S = input_ids.shape[1]
        prefix_len = jnp.asarray(unwrap(prefix_len), jnp.int32)
        length = jnp.asarray(unwrap(length), jnp.int32)
        # absolute positions of the suffix tokens; the padded tail may
        # run past max_position_embeddings — clip it into the table
        # (garbage rows the causal mask and length slice never expose)
        pos = jnp.clip(prefix_len + jnp.arange(S, dtype=jnp.int32),
                       0, cfg.max_position_embeddings - 1)
        x = gpt.drop(gpt.wte(input_ids) + gpt.wpe(Tensor(pos)))
        ks, vs = [], []
        for i, blk in enumerate(gpt.h):
            x, k, v = blk.prefill_prefix(x, prefix_k[i], prefix_v[i],
                                         prefix_len)
            ks.append(unwrap(k)[0])
            vs.append(unwrap(v)[0])
        hidden = gpt.ln_f(x)                         # [1, Ss, H]
        last = lax.dynamic_slice_in_dim(
            unwrap(hidden), length - prefix_len - 1, 1, axis=1)
        logits = self._head(Tensor(last))
        return jnp.stack(ks), jnp.stack(vs), unwrap(logits)[0, 0]

    def _beam_traced(self, input_ids, max_new_tokens, num_beams,
                     eos_token_id):
        """jit-traced beam search over the KV cache: beams live as an
        expanded batch [B*W]; each step expands W*V candidates through
        text.beam_search_step (the beam_search_op.cc redesign), reorders
        the caches along the surviving parents, and the final sequences
        are backtracked with text.gather_tree (gather_tree_op.cc)."""
        import jax
        import jax.numpy as jnp

        from ..tensor import unwrap
        from ..text import beam_search_decode, beam_search_step

        B, S = input_ids.shape[0], input_ids.shape[1]
        W = int(num_beams)
        V = self.cfg.vocab_size
        cache_len = S + int(max_new_tokens)
        eos = V if eos_token_id is None else int(eos_token_id)  # V = never

        ids = unwrap(input_ids)
        # prefill ONCE per prompt; beams only diverge after the first
        # expansion, so the caches/last-hidden just repeat along batch
        hidden, caches = self.gpt.prefill(input_ids, cache_len)
        caches = tuple((jnp.repeat(k, W, axis=0), jnp.repeat(v, W, axis=0))
                       for k, v in caches)

        def log_probs(hidden):
            lg = unwrap(self._head(hidden))[:, -1]            # [B*W, V]
            return jax.nn.log_softmax(lg, axis=-1).reshape(B, W, V)

        lg0 = unwrap(self._head(hidden[:, -1:]))[:, -1]       # [B, V]
        lp0 = jnp.broadcast_to(
            jax.nn.log_softmax(lg0, axis=-1)[:, None, :], (B, W, V))
        scores0 = jnp.full((B, W), jnp.finfo(jnp.float32).min,
                           jnp.float32).at[:, 0].set(0.0)
        finished0 = jnp.zeros((B, W), bool)
        batch_base = (jnp.arange(B, dtype=jnp.int32)[:, None] * W)

        def step(carry, _):
            lp, scores, finished, caches, pos = carry
            tok, parents, scores = (
                unwrap(t) for t in beam_search_step(
                    Tensor(lp), Tensor(scores), W, end_token=eos,
                    finished=Tensor(finished)))
            tok = tok.astype(jnp.int32)
            parents = parents.astype(jnp.int32)
            sel = (batch_base + parents).reshape(-1)          # [B*W]
            finished = jnp.take_along_axis(finished, parents, axis=1) \
                | (tok == eos)
            caches = tuple((k[sel], v[sel]) for k, v in caches)
            hidden, caches = self.gpt.decode_step(
                Tensor(tok.reshape(B * W, 1)), pos, caches)
            return ((log_probs(hidden), scores, finished, caches, pos + 1),
                    (tok, parents))

        (_, scores, _, _, _), (toks, parents) = jax.lax.scan(
            step, (lp0, scores0, finished0, caches,
                   jnp.asarray(S, jnp.int32)),
            None, length=int(max_new_tokens))
        # backtrack surviving paths (beam_search_decode_op analog)
        seqs, scores = beam_search_decode(Tensor(toks), Tensor(parents),
                                          Tensor(scores))
        best = jnp.argmax(unwrap(scores), axis=1)             # [B]
        seq = jnp.take_along_axis(
            unwrap(seqs), best[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]
        return jnp.concatenate([ids, seq.astype(jnp.int32)], axis=1)

    def _generate_traced(self, input_ids, rng, max_new_tokens, temperature,
                         top_k, do_sample, eos_token_id):
        """jit-traced generation body: batched prefill, then lax.scan
        single-token decode over static-size KV caches — the
        TPU-idiomatic serving loop (static shapes, no per-step dispatch;
        the reference's dynamic while_loop + beam_search_op decoders,
        operators/beam_search_op.cc, trade shape dynamism for host
        round-trips that ICI latency makes prohibitive here)."""
        import jax
        import jax.numpy as jnp

        from ..tensor import unwrap

        B, S = input_ids.shape[0], input_ids.shape[1]
        cache_len = S + int(max_new_tokens)
        V = self.cfg.vocab_size
        eos = V if eos_token_id is None else int(eos_token_id)  # V = never

        def sample(logits, key):
            logits = unwrap(logits)[:, -1]            # [B, V]
            if not do_sample:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = logits / jnp.maximum(temperature, 1e-6)
            if top_k and top_k > 0:
                kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
                logits = jnp.where(logits < kth,
                                   jnp.finfo(logits.dtype).min, logits)
            return jax.random.categorical(key, logits).astype(jnp.int32)

        hidden, caches = self.gpt.prefill(input_ids, cache_len)
        key, sub = jax.random.split(rng)
        tok = sample(self._head(hidden[:, -1:]), sub)  # first new token
        finished = tok == eos

        def step(carry, _):
            tok, finished, pos, caches, key = carry
            key, sub = jax.random.split(key)
            hidden, caches = self.gpt.decode_step(
                Tensor(tok[:, None]), pos, caches)
            nxt = sample(self._head(hidden), sub)
            nxt = jnp.where(finished, jnp.int32(eos), nxt)  # pad past eos
            finished = finished | (nxt == eos)
            return (nxt, finished, pos + 1, caches, key), tok

        (last, _, _, _, _), toks = jax.lax.scan(
            step, (tok, finished, jnp.asarray(S, jnp.int32), caches, key),
            None, length=int(max_new_tokens) - 1)
        toks = jnp.concatenate(
            [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)  # [B, new]
        return jnp.concatenate([unwrap(input_ids), toks], axis=1)

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=0, do_sample=False, seed=0, num_beams=1,
                 eos_token_id=None):
        """Autoregressive generation with a static KV cache.

        Greedy by default; ``do_sample=True`` enables temperature / top-k
        categorical sampling; ``num_beams > 1`` runs beam search (length
        penalty not applied; finished beams propose only
        ``eos_token_id``).  The whole loop (prefill + every decode step)
        compiles to ONE XLA program per (batch, prompt_len,
        max_new_tokens, mode) shape — cached across calls in a per-shape
        dict.  Returns [B, prompt_len + max_new_tokens] int32 token ids
        (prompt included), matching the HF/paddlenlp generate contract.
        """
        import jax
        import numpy as np

        from ..nn.layer_base import functional_call, state_pytrees

        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if num_beams > 1 and do_sample:
            raise ValueError("beam search and sampling are exclusive "
                             "(num_beams > 1 with do_sample=True)")
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(np.asarray(input_ids, np.int32))
        if ids.shape[1] + int(max_new_tokens) \
                > self.cfg.max_position_embeddings:
            raise ValueError(
                f"prompt {ids.shape[1]} + max_new_tokens {max_new_tokens} "
                f"exceeds max_position_embeddings "
                f"{self.cfg.max_position_embeddings}")
        was_training = self.training
        self.eval()
        try:
            params, buffers = state_pytrees(self)
            # sampling knobs only shape the program when do_sample is on
            key_static = (ids.shape[0], ids.shape[1], int(max_new_tokens),
                          bool(do_sample), int(num_beams),
                          None if eos_token_id is None else int(eos_token_id),
                          (float(temperature), int(top_k))
                          if do_sample else None)
            cache = getattr(self, "_gen_cache", None)
            if cache is None:
                cache = self._gen_cache = {}
            if key_static not in cache:
                if num_beams > 1:
                    def run(params, ids_arr, rng):
                        out, _ = functional_call(
                            self, params,
                            (Tensor(ids_arr), max_new_tokens, num_beams,
                             eos_token_id),
                            buffers=buffers, mutable=False,
                            method="_beam_traced")
                        return out
                else:
                    def run(params, ids_arr, rng):
                        out, _ = functional_call(
                            self, params,
                            (Tensor(ids_arr), rng, max_new_tokens,
                             temperature, top_k, do_sample, eos_token_id),
                            buffers=buffers, mutable=False,
                            method="_generate_traced")
                        return out

                cache[key_static] = jax.jit(run)
            fn = cache[key_static]
            rng = jax.random.PRNGKey(seed)
            return Tensor(fn(params, ids.value.astype("int32"), rng))
        finally:
            if was_training:
                self.train()
