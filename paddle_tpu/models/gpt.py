"""GPT decoder-only language model, tensor-parallel-ready.

Workload parity: BASELINE.md config 5 (GPT-3 1.3B with TP+PP).  The reference
tree has no GPT implementation (it lives in PaddleNLP); this is the TPU-native
flagship: GSPMD tensor parallelism via the meta_parallel layers (weights carry
PartitionSpecs; XLA inserts the Megatron collectives), optional
sequence-parallel ring attention for long context, fused attention via the
Pallas flash kernel on TPU (ops/fused.scaled_dot_product_attention).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import tensor_ops as T
from ..distributed.meta_parallel import (ColumnParallelLinear,
                                         RowParallelLinear,
                                         VocabParallelEmbedding,
                                         shard_constraint)
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer, ParamAttr
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.norm import LayerNorm
from ..ops import fused
from ..tensor import Tensor


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden_size: int | None = None  # default 4*hidden
    max_position_embeddings: int = 1024
    dropout: float = 0.1
    attn_dropout: float = 0.1
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    tensor_parallel: bool = False   # annotate weights for an `mp` mesh axis
    sequence_parallel: bool = False  # ring attention over an `sp` mesh axis
    tie_word_embeddings: bool = True
    recompute: bool = False  # remat each block (fluid RecomputeOptimizer,
                             # optimizer.py:4533) — activations between
                             # blocks are the only saved residuals

    @property
    def ffn_size(self):
        return self.ffn_hidden_size or 4 * self.hidden_size


def _init(cfg):
    return ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range))


class GPTAttention(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        H = cfg.hidden_size
        if cfg.tensor_parallel:
            self.qkv = ColumnParallelLinear(H, 3 * H, weight_attr=_init(cfg),
                                            gather_output=False)
            self.out = RowParallelLinear(H, H, weight_attr=_init(cfg),
                                         input_is_parallel=True)
        else:
            self.qkv = Linear(H, 3 * H, weight_attr=_init(cfg))
            self.out = Linear(H, H, weight_attr=_init(cfg))
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x):
        cfg = self.cfg
        B, S = x.shape[0], x.shape[1]
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = self.qkv(x)
        qkv = T.reshape(qkv, [B, S, 3, nh, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.tensor_parallel:
            # heads follow the qkv column shards
            q = shard_constraint(q, None, None, "mp", None)
            k = shard_constraint(k, None, None, "mp", None)
            v = shard_constraint(v, None, None, "mp", None)
        if cfg.sequence_parallel:
            from ..ops.ring_attention import ring_attention

            ctx = ring_attention(q, k, v, causal=True)
        else:
            ctx = fused.scaled_dot_product_attention(
                q, k, v, dropout_p=cfg.attn_dropout, is_causal=True,
                training=self.training)
        ctx = T.reshape(ctx, [B, S, cfg.hidden_size])
        return self.dropout(self.out(ctx))


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        H, FF = cfg.hidden_size, cfg.ffn_size
        if cfg.tensor_parallel:
            self.fc1 = ColumnParallelLinear(H, FF, weight_attr=_init(cfg),
                                            gather_output=False)
            self.fc2 = RowParallelLinear(FF, H, weight_attr=_init(cfg),
                                         input_is_parallel=True)
        else:
            self.fc1 = Linear(H, FF, weight_attr=_init(cfg))
            self.fc2 = Linear(FF, H, weight_attr=_init(cfg))
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x):
        return self.dropout(self.fc2(F.gelu(self.fc1(x))))


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.attn = GPTAttention(cfg)
        self.ln_2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.mlp = GPTMLP(cfg)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tensor_parallel:
            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                              weight_attr=_init(cfg))
        else:
            self.wte = Embedding(cfg.vocab_size, cfg.hidden_size,
                                 weight_attr=_init(cfg))
        self.wpe = Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                             weight_attr=_init(cfg))
        self.drop = Dropout(cfg.dropout)
        self.h = [GPTBlock(cfg) for _ in range(cfg.num_layers)]
        for i, blk in enumerate(self.h):
            self.add_sublayer(f"h_{i}", blk)
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)

    def forward(self, input_ids):
        import paddle_tpu as paddle

        pos = paddle.arange(input_ids.shape[1])
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if self.cfg.recompute:
            from ..distributed.recompute import recompute as _remat
            for blk in self.h:
                x = _remat(blk, x)
        else:
            for blk in self.h:
                x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                  weight_attr=_init(cfg), bias_attr=False)

    def forward(self, input_ids, labels=None):
        hidden = self.gpt(input_ids)
        if self.cfg.tie_word_embeddings:
            logits = T.matmul(hidden, T.transpose(self.gpt.wte.weight, [1, 0]))
        else:
            logits = self.lm_head(hidden)
        if labels is None:
            return logits
        loss = fused.softmax_cross_entropy(
            logits[:, :-1], labels[:, 1:])
        return logits, T.mean(loss)

    def loss(self, input_ids):
        """Next-token LM loss on a batch of token ids, via the chunked
        fused LM-head matmul + cross entropy (ops/fused.py
        fused_linear_cross_entropy) — the fp32 [B*S, V] logits never
        materialize in HBM at once."""
        hidden = self.gpt(input_ids)
        if self.cfg.tie_word_embeddings:
            w = T.transpose(self.gpt.wte.weight, [1, 0])
        else:
            w = self.lm_head.weight
        loss = fused.fused_linear_cross_entropy(
            hidden[:, :-1], w, input_ids[:, 1:])
        return T.mean(loss)
