"""HuggingFace checkpoint interop for the model zoo.

Load transformers BertModel / GPT2LMHeadModel weights (a live torch
module or its state_dict) into the paddle_tpu models.  The mappings are
the ones the parity suite verifies to ~1e-5 (tests/test_bert_hf_parity,
test_gpt_hf_parity): paddle Linear stores [in, out] so HF's [out, in]
Linear weights transpose on the way in, while GPT-2's Conv1D already
matches; qkv unpack from in_proj/c_attn.

Reference analog: the paddlenlp `from_pretrained` conversion tables —
here a direct functional mapping, no hub access (zero-egress friendly:
pass a locally loaded model/state_dict).
"""
from __future__ import annotations

import numpy as np

__all__ = ["load_hf_bert", "load_hf_gpt2", "to_hf_bert_state",
           "to_hf_gpt2_state"]


def _np(t):
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _state(src):
    if hasattr(src, "state_dict"):
        src = src.state_dict()
    return {k: _np(v) for k, v in src.items()}


def _set(param, value, transpose=False):
    value = value.T if transpose else value
    if tuple(param.shape) != tuple(value.shape):
        raise ValueError(f"shape mismatch: model {tuple(param.shape)} vs "
                         f"checkpoint {tuple(value.shape)}")
    param.set_value(np.ascontiguousarray(value))


def load_hf_bert(model, hf_source, strict=True):
    """Load a transformers BertModel (or its state_dict) into a
    paddle_tpu BertModel.  Returns the model."""
    sd = _state(hf_source)
    pre = "bert." if any(k.startswith("bert.") for k in sd) else ""

    def g(name):
        return sd[pre + name]

    emb = model.embeddings
    _set(emb.word.weight, g("embeddings.word_embeddings.weight"))
    _set(emb.position.weight, g("embeddings.position_embeddings.weight"))
    _set(emb.token_type.weight,
         g("embeddings.token_type_embeddings.weight"))
    _set(emb.layer_norm.weight, g("embeddings.LayerNorm.weight"))
    _set(emb.layer_norm.bias, g("embeddings.LayerNorm.bias"))
    for i, pl in enumerate(model.encoder.layers):
        p = f"encoder.layer.{i}."
        _set(pl.self_attn.q_proj.weight,
             g(p + "attention.self.query.weight"), transpose=True)
        _set(pl.self_attn.q_proj.bias, g(p + "attention.self.query.bias"))
        _set(pl.self_attn.k_proj.weight,
             g(p + "attention.self.key.weight"), transpose=True)
        _set(pl.self_attn.k_proj.bias, g(p + "attention.self.key.bias"))
        _set(pl.self_attn.v_proj.weight,
             g(p + "attention.self.value.weight"), transpose=True)
        _set(pl.self_attn.v_proj.bias, g(p + "attention.self.value.bias"))
        _set(pl.self_attn.out_proj.weight,
             g(p + "attention.output.dense.weight"), transpose=True)
        _set(pl.self_attn.out_proj.bias,
             g(p + "attention.output.dense.bias"))
        _set(pl.norm1.weight, g(p + "attention.output.LayerNorm.weight"))
        _set(pl.norm1.bias, g(p + "attention.output.LayerNorm.bias"))
        _set(pl.linear1.weight, g(p + "intermediate.dense.weight"),
             transpose=True)
        _set(pl.linear1.bias, g(p + "intermediate.dense.bias"))
        _set(pl.linear2.weight, g(p + "output.dense.weight"),
             transpose=True)
        _set(pl.linear2.bias, g(p + "output.dense.bias"))
        _set(pl.norm2.weight, g(p + "output.LayerNorm.weight"))
        _set(pl.norm2.bias, g(p + "output.LayerNorm.bias"))
    if pre + "pooler.dense.weight" in sd:
        _set(model.pooler.weight, g("pooler.dense.weight"), transpose=True)
        _set(model.pooler.bias, g("pooler.dense.bias"))
    elif strict:
        raise KeyError("checkpoint has no pooler weights "
                       "(pass strict=False to skip)")
    return model


def load_hf_gpt2(model, hf_source, strict=True):
    """Load a transformers GPT2LMHeadModel / GPT2Model (or state_dict)
    into a paddle_tpu GPTForCausalLM.  Returns the model.

    HF GPT-2 always ties lm_head to wte, so the tied configuration is
    exact; an untied paddle model needs a checkpoint carrying
    lm_head.weight (raises under strict=True when absent — a silently
    random LM head would generate garbage with no indication)."""
    sd = _state(hf_source)
    pre = "transformer." if any(k.startswith("transformer.")
                                for k in sd) else ""

    def g(name):
        return sd[pre + name]

    gpt = model.gpt
    _set(gpt.wte.weight, g("wte.weight"))
    _set(gpt.wpe.weight, g("wpe.weight"))
    _set(gpt.ln_f.weight, g("ln_f.weight"))
    _set(gpt.ln_f.bias, g("ln_f.bias"))
    for i, pb in enumerate(gpt.h):
        p = f"h.{i}."
        _set(pb.ln_1.weight, g(p + "ln_1.weight"))
        _set(pb.ln_1.bias, g(p + "ln_1.bias"))
        _set(pb.ln_2.weight, g(p + "ln_2.weight"))
        _set(pb.ln_2.bias, g(p + "ln_2.bias"))
        # GPT-2 Conv1D stores [in, out] — the paddle convention already
        _set(pb.attn.qkv.weight, g(p + "attn.c_attn.weight"))
        _set(pb.attn.qkv.bias, g(p + "attn.c_attn.bias"))
        _set(pb.attn.out.weight, g(p + "attn.c_proj.weight"))
        _set(pb.attn.out.bias, g(p + "attn.c_proj.bias"))
        _set(pb.mlp.fc1.weight, g(p + "mlp.c_fc.weight"))
        _set(pb.mlp.fc1.bias, g(p + "mlp.c_fc.bias"))
        _set(pb.mlp.fc2.weight, g(p + "mlp.c_proj.weight"))
        _set(pb.mlp.fc2.bias, g(p + "mlp.c_proj.bias"))
    if not model.cfg.tie_word_embeddings:
        if "lm_head.weight" in sd:
            _set(model.lm_head.weight, sd["lm_head.weight"],
                 transpose=True)
        elif strict:
            raise KeyError(
                "checkpoint has no lm_head.weight but the model is "
                "untied (tie_word_embeddings=False) — the LM head would "
                "stay randomly initialized; pass strict=False to accept")
    return model


# --- export direction: paddle_tpu -> HF state_dict -------------------------


def _arr(p, transpose=False):
    a = np.asarray(p.numpy())
    return np.ascontiguousarray(a.T) if transpose else a


def to_hf_bert_state(model):
    """numpy state_dict in transformers BertModel naming — load with
    ``hf.load_state_dict({k: torch.tensor(v) for k, v in out.items()})``.
    Round-trip verified by the interop tests."""
    sd = {}
    emb = model.embeddings
    sd["embeddings.word_embeddings.weight"] = _arr(emb.word.weight)
    sd["embeddings.position_embeddings.weight"] = _arr(
        emb.position.weight)
    sd["embeddings.token_type_embeddings.weight"] = _arr(
        emb.token_type.weight)
    sd["embeddings.LayerNorm.weight"] = _arr(emb.layer_norm.weight)
    sd["embeddings.LayerNorm.bias"] = _arr(emb.layer_norm.bias)
    for i, pl in enumerate(model.encoder.layers):
        p = f"encoder.layer.{i}."
        for hf_name, lin in [("attention.self.query", pl.self_attn.q_proj),
                             ("attention.self.key", pl.self_attn.k_proj),
                             ("attention.self.value", pl.self_attn.v_proj),
                             ("attention.output.dense",
                              pl.self_attn.out_proj),
                             ("intermediate.dense", pl.linear1),
                             ("output.dense", pl.linear2)]:
            sd[p + hf_name + ".weight"] = _arr(lin.weight, transpose=True)
            sd[p + hf_name + ".bias"] = _arr(lin.bias)
        sd[p + "attention.output.LayerNorm.weight"] = _arr(pl.norm1.weight)
        sd[p + "attention.output.LayerNorm.bias"] = _arr(pl.norm1.bias)
        sd[p + "output.LayerNorm.weight"] = _arr(pl.norm2.weight)
        sd[p + "output.LayerNorm.bias"] = _arr(pl.norm2.bias)
    sd["pooler.dense.weight"] = _arr(model.pooler.weight, transpose=True)
    sd["pooler.dense.bias"] = _arr(model.pooler.bias)
    return sd


def to_hf_gpt2_state(model):
    """numpy state_dict in transformers GPT2Model naming (add the
    ``transformer.`` prefix + tied ``lm_head.weight`` yourself for
    GPT2LMHeadModel)."""
    gpt = model.gpt
    sd = {"wte.weight": _arr(gpt.wte.weight),
          "wpe.weight": _arr(gpt.wpe.weight),
          "ln_f.weight": _arr(gpt.ln_f.weight),
          "ln_f.bias": _arr(gpt.ln_f.bias)}
    for i, pb in enumerate(gpt.h):
        p = f"h.{i}."
        sd[p + "ln_1.weight"] = _arr(pb.ln_1.weight)
        sd[p + "ln_1.bias"] = _arr(pb.ln_1.bias)
        sd[p + "ln_2.weight"] = _arr(pb.ln_2.weight)
        sd[p + "ln_2.bias"] = _arr(pb.ln_2.bias)
        sd[p + "attn.c_attn.weight"] = _arr(pb.attn.qkv.weight)
        sd[p + "attn.c_attn.bias"] = _arr(pb.attn.qkv.bias)
        sd[p + "attn.c_proj.weight"] = _arr(pb.attn.out.weight)
        sd[p + "attn.c_proj.bias"] = _arr(pb.attn.out.bias)
        sd[p + "mlp.c_fc.weight"] = _arr(pb.mlp.fc1.weight)
        sd[p + "mlp.c_fc.bias"] = _arr(pb.mlp.fc1.bias)
        sd[p + "mlp.c_proj.weight"] = _arr(pb.mlp.fc2.weight)
        sd[p + "mlp.c_proj.bias"] = _arr(pb.mlp.fc2.bias)
    if not model.cfg.tie_word_embeddings:
        sd["lm_head.weight"] = _arr(model.lm_head.weight, transpose=True)
    return sd
