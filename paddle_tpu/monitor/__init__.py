"""paddle_tpu.monitor — unified runtime telemetry for training jobs.

The platform observability layer (PAPER.md layer 1: platform/profiler.*
RecordEvent scopes, DeviceTracer, tools/timeline.py chrome traces)
rebuilt TPU-native as one surface over the shared metrics registry
(`utils/metrics.py`):

  * `TrainTelemetry` — per-step metrics (loss, lr, phase times, MFU,
    samples/s, device memory), a rotating JSONL event log under
    `FLAGS_telemetry_dir`, and bounded on-demand jax.profiler captures.
  * `MonitorServer`  — /metrics (Prometheus), /healthz, and
    /debug/trace?steps=N against a RUNNING fit; the launcher federates
    per-rank endpoints into one.
  * SIGUSR1 — the headless /debug/trace equivalent.

`Model.fit` wires all of it automatically when `FLAGS_telemetry_dir` is
set and/or `FLAGS_monitor_port` >= 0; see README "Observability".
"""
from __future__ import annotations

import logging
import threading

from ..framework import flags as _flags
from ..utils.metrics import default_registry
from . import flightrec, perf, tracing
from .flightrec import FlightRecorder
from .server import MonitorServer, runtime_health
from .telemetry import (PEAK_FLOPS, JsonlWriter, TrainTelemetry,
                        device_memory_stats, install_sigusr1,
                        peak_flops_per_device)
from .tracing import NullSpan, Span, Tracer, default_tracer

logger = logging.getLogger("paddle_tpu.monitor")

__all__ = ["TrainTelemetry", "MonitorServer", "JsonlWriter", "PEAK_FLOPS",
           "peak_flops_per_device", "device_memory_stats",
           "install_sigusr1", "default_registry", "fit_monitor",
           "get_monitor_server", "get_telemetry", "reset",
           "runtime_health",
           "Tracer", "Span", "NullSpan", "default_tracer",
           "FlightRecorder", "tracing", "flightrec", "perf"]

_lock = threading.Lock()
_telemetry: TrainTelemetry | None = None
_server: MonitorServer | None = None


def fit_monitor():
    """The process-wide (telemetry, server) pair Model.fit attaches to,
    created lazily from flags.  Returns (None, None) when both
    `FLAGS_telemetry_dir` and `FLAGS_monitor_port` are off — the fit
    loop then skips every telemetry hook (zero overhead).

    Singleton by design: gauges live in the shared default registry and
    the HTTP port is bound once; a second fit in the same process reuses
    both (the JSONL log simply grows more fit_begin/fit_end markers)."""
    global _telemetry, _server
    tdir = str(_flags.flag("FLAGS_telemetry_dir") or "")
    port = int(_flags.flag("FLAGS_monitor_port", -1))
    if not tdir and port < 0:
        return None, None
    with _lock:
        if _telemetry is None:
            _telemetry = TrainTelemetry(telemetry_dir=tdir or None)
            if tdir:
                # crash flight recorder rides along whenever the event
                # log is on: spans mirror into its ring, and the
                # excepthook/atexit hooks leave a postmortem dump
                rec = flightrec.configure(tdir)
                flightrec.install_hooks()
                perf.install_oom_hook()
                default_tracer().add_listener(rec.on_span)
        if _server is None and port >= 0:
            try:
                _server = MonitorServer(telemetry=_telemetry,
                                        port=port).start()
            except OSError as e:
                logger.error("monitor server failed to bind port %s: %s "
                             "— metrics endpoint disabled, telemetry "
                             "continues", port, e)
                _server = None
        elif _server is not None:
            _server.telemetry = _telemetry
        return _telemetry, _server


def get_monitor_server():
    return _server


def get_telemetry():
    """The live TrainTelemetry, or None when no monitored fit has
    started — existence check only, never creates (fit_monitor
    does)."""
    return _telemetry


def reset():
    """Tear down the process singletons (tests)."""
    global _telemetry, _server
    with _lock:
        if _server is not None:
            _server.shutdown()
            _server = None
        if _telemetry is not None:
            _telemetry.close()
            _telemetry = None
    tracing.reset()
    flightrec.reset()
    perf.reset()
