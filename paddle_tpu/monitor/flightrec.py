# pta: jax-free
"""Crash flight recorder: a bounded in-memory ring of recent spans,
window summaries, and ckpt/NaN events, dumped to
`FLAGS_telemetry_dir/flightrec-<pid>.json` when the process dies.

PR 6's telemetry is aggregate-only — after a watchdog exit 86 or a
durability exit 91 the artifacts are summary histograms and whatever
scrolled past in the log.  The recorder keeps the last
`FLAGS_flightrec_records` discrete events (pure-python dicts, jax-free
so recording from the checkpoint writer thread is safe) and writes one
JSON postmortem on the way down:

  * watchdog exit 86   — resilience.Watchdog dumps from its monitor
                         thread BEFORE os._exit (os._exit skips atexit)
  * durability exit 91 / preemption exit 75
                       — dumped at the raise sites (SystemExit does not
                         reach sys.excepthook)
  * serving drain      — ServingServer.shutdown dumps after the engines
                         stop
  * uncaught crash     — a chained sys.excepthook
  * normal exit        — an atexit fallback, so HEALTHY ranks also
                         leave their accounting for the launcher's
                         goodput ledger

Signal discipline (PTA003): nothing here registers a signal handler and
nothing here may be called FROM one — handlers latch an int (see
`latch_exit`, a single assignment) and the dump happens from regular
code (watchdog thread, training thread poll, atexit).

The dump embeds a goodput pre-accounting derived from the shared
metrics registry — wall_s vs train_s (step-histogram sum) vs compile_s
(first-step gauge) vs ckpt_stall_s — which `distributed/goodput.py`
aggregates across ranks and restarts.
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import socket
import sys
import threading
import time
import traceback

from ..framework import flags as _flags
from ..utils.metrics import default_registry

__all__ = ["FlightRecorder", "configure", "get_recorder", "record",
           "dump", "latch_exit", "install_hooks", "reset"]

DUMP_VERSION = 1

# exit-code → dump reason for the atexit fallback (values mirror
# distributed/resilience.py PREEMPTED/WATCHDOG/DURABILITY exit codes;
# literal ints to keep this module import-light and jax-free)
_EXIT_REASONS = {75: "preempt", 86: "watchdog", 91: "durability"}


class FlightRecorder:
    """Bounded ring of recent runtime events + one-shot JSON dump."""

    def __init__(self, directory: str = None, max_records: int = None):
        if directory is None:
            directory = str(_flags.flag("FLAGS_telemetry_dir") or "") or "."
        if max_records is None:
            max_records = int(
                _flags.flag("FLAGS_flightrec_records", 512) or 512)
        self.directory = directory
        self._records = collections.deque(maxlen=max(1, int(max_records)))
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._t0_wall = time.time()
        self.dumped_reason = None      # set by the first successful dump
        self.exit_latch = 0            # int mailbox a signal handler MAY
        #                                assign (never read from one)

    # -- recording (any thread; pure-python, lock + deque append) ----------
    def record(self, kind: str, **fields):
        rec = {"ts": round(time.time(), 3), "kind": kind}
        rec.update(fields)
        with self._lock:
            self._records.append(rec)

    def on_span(self, span: dict):
        """Tracer listener: mirror every finished span into the ring."""
        self.record("span", name=span["name"], trace_id=span["trace_id"],
                    span_id=span["span_id"], parent_id=span["parent_id"],
                    dur_ms=span["dur_ms"], attrs=span["attrs"] or {})

    def records(self, kind: str = None) -> list[dict]:
        with self._lock:
            out = list(self._records)
        if kind is not None:
            out = [r for r in out if r["kind"] == kind]
        return out

    def __len__(self):
        return len(self._records)

    # -- accounting for the goodput ledger ---------------------------------
    def accounting(self, snap: dict = None) -> dict:
        if snap is None:
            try:
                snap = default_registry().snapshot()
            except Exception:  # noqa: BLE001 - last-gasp path
                snap = {}

        def hist_s(name):
            v = snap.get(name)
            return float(v["sum"]) / 1e3 if isinstance(v, dict) else 0.0

        def gauge_s(name):
            v = snap.get(name)
            return float(v) / 1e3 if isinstance(v, (int, float)) else 0.0

        return {
            "wall_s": round(time.monotonic() - self._t0, 3),
            "train_s": round(hist_s("paddle_train_step_ms"), 3),
            "compile_s": round(gauge_s("paddle_train_first_step_ms"), 3),
            "ckpt_stall_s": round(hist_s("paddle_ckpt_step_stall_ms"), 3),
        }

    # -- the dump ----------------------------------------------------------
    def dump_path(self) -> str:
        return os.path.join(self.directory, f"flightrec-{os.getpid()}.json")

    def dump(self, reason: str, extra: dict = None) -> str:
        """Write the postmortem atomically (tmp + rename); later dumps
        overwrite earlier ones, so the terminal reason wins."""
        try:
            snap = default_registry().snapshot()
        except Exception:  # noqa: BLE001 - keep the ring even if a
            snap = {}      # computed gauge fn is broken
        doc = {
            "version": DUMP_VERSION,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "reason": reason,
            "ts": round(time.time(), 3),
            "started_at": round(self._t0_wall, 3),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "accounting": self.accounting(snap),
            "metrics": snap,
            "records": self.records(),
        }
        if extra:
            doc.update(extra)
        os.makedirs(self.directory, exist_ok=True)
        path = self.dump_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
            f.write("\n")
        os.replace(tmp, path)
        self.dumped_reason = reason
        return path


# -- process singleton + last-gasp hooks -----------------------------------
_recorder: FlightRecorder | None = None
_lock = threading.Lock()
_hooks_installed = False
_prev_excepthook = None
_enrichers: list = []


def add_enricher(fn):
    """Register a crash-dump enricher: ``fn(exc_type, exc)`` returning
    None (not interested) or ``{"reason": str, "extra": dict}`` merged
    into the crash dump — how monitor.perf turns a RESOURCE_EXHAUSTED
    crash into an "oom" dump carrying the buffer census.  Enrichers run
    inside the excepthook's try, on the crashing thread; this module
    stays jax-free, the callable may not be.  Idempotent per
    function."""
    if fn not in _enrichers:
        _enrichers.append(fn)


def configure(directory: str = None, max_records: int = None) \
        -> FlightRecorder:
    """Create (or retarget) the process-wide recorder.  Idempotent:
    called from monitor.fit_monitor() and the serving/launcher entry
    points; the first caller sizes the ring."""
    global _recorder
    with _lock:
        if _recorder is None:
            _recorder = FlightRecorder(directory=directory,
                                       max_records=max_records)
        elif directory:
            _recorder.directory = directory
        return _recorder


def get_recorder() -> FlightRecorder | None:
    return _recorder


def record(kind: str, **fields):
    """Record into the process recorder; silently a no-op before
    configure() — instrumentation sites never need to guard."""
    r = _recorder
    if r is not None:
        r.record(kind, **fields)


def dump(reason: str, extra: dict = None):
    """Dump the process recorder; returns the path or None.  Never
    raises — this runs on the way down and must not mask the original
    failure."""
    r = _recorder
    if r is None:
        return None
    try:
        return r.dump(reason, extra=extra)
    except Exception:  # noqa: BLE001 - last-gasp path
        return None


def latch_exit(code: int):
    """Async-signal-safe: a single int assignment a signal handler may
    perform so the atexit fallback can name the reason.  Everything
    else (locks, IO, json) happens OUTSIDE handlers."""
    r = _recorder
    if r is not None:
        r.exit_latch = code


def _excepthook(exc_type, exc, tb):
    r = _recorder
    if r is not None and not issubclass(exc_type, SystemExit):
        try:
            frames = traceback.format_exception(exc_type, exc, tb)
            r.record("exception", type=exc_type.__name__,
                     msg=str(exc)[:500])
            reason, extra = "crash", {"exception": {
                "type": exc_type.__name__,
                "msg": str(exc)[:500],
                "traceback": frames[-30:]}}
            for fn in list(_enrichers):
                try:
                    out = fn(exc_type, exc)
                except Exception:  # noqa: BLE001 - enrichment is optional
                    continue
                if out:
                    reason = out.get("reason", reason)
                    extra.update(out.get("extra", {}))
            r.dump(reason, extra=extra)
        except Exception:  # noqa: BLE001 - never mask the real crash
            pass
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _atexit_dump():
    r = _recorder
    if r is None or r.dumped_reason is not None:
        return
    reason = _EXIT_REASONS.get(r.exit_latch, "exit")
    if reason == "exit" and not len(r):
        return  # recorder configured but nothing ever happened
    try:
        r.dump(reason)
    except Exception:  # noqa: BLE001 - last-gasp path
        pass


def install_hooks():
    """Chain sys.excepthook (uncaught crash) and register the atexit
    fallback (normal exit + sys.exit paths, which excepthook never
    sees).  Idempotent."""
    global _hooks_installed, _prev_excepthook
    with _lock:
        if _hooks_installed:
            return
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
        atexit.register(_atexit_dump)
        _hooks_installed = True


def reset():
    """Drop the process recorder and enrichers (tests).  Installed
    hooks stay but no-op while the recorder is None."""
    global _recorder
    with _lock:
        _recorder = None
        del _enrichers[:]
