"""Per-op performance attribution, HBM accounting, and OOM postmortem.

Reference parity: `fluid.profiler.profiler()` + `tools/timeline.py` gave
the reference stack an op-level view (which operator burned the time)
and gperftools gave it heap attribution.  Under XLA neither exists as a
library surface — the unit of execution is an HLO instruction inside a
fused module, and device memory is opaque PJRT buffers.  This module
rebuilds both views from what XLA *does* expose:

  * **op table** — the compiled step's HLO text (``compiled.as_text()``)
    is parsed into per-instruction analytic costs (dot/conv flops,
    elementwise flops, transcendentals, boundary bytes — the same
    accounting ``HloCostAnalysis`` uses, which is why the summed table
    matches ``cost_analysis()['flops']``), then joined with measured
    per-op times from a bounded ``jax.profiler`` capture: XLA's thunk
    executor emits one trace event per entry instruction, named after
    it, so ``dot.8`` in the table meets ``dot.8`` in the trace.  Ops the
    trace did not cover get the measured step wall attributed
    proportionally to their roofline cost.  Each row carries the
    achieved fraction of roofline and a compute/memory/collective-bound
    classification (arithmetic intensity vs. the device ridge point).
  * **buffer census** — ``jax.live_arrays()`` bucketed by
    (owner tag, dtype, shape).  Owner tags come from registered
    suppliers (the train engine tags params/opt state/buffers, the
    generation engine tags params/KV pages); device arrays nobody claims
    are ``activations`` — in a training process that residue is
    activations, inputs, and XLA temporaries.  This is the accounting
    surface the paged-KV work will report page occupancy into.
  * **OOM postmortem** — a ``RESOURCE_EXHAUSTED`` escaping to the crash
    hook (or caught by an engine thread) dumps the census plus every
    registered op report into the flight recorder under reason
    ``"oom"``, so the first question after an OOM ("what was resident,
    what was the step doing") is answered by a file, not a rerun.

Module-level registries (`register_provider` / `register_owner`) let
engines publish their reports without the monitor server holding engine
references; `MonitorServer GET /debug/perf` serves `collect_reports()`
and `?format=chrome` merges the op timeline into the span export so one
perfetto load shows request spans AND device ops.
"""
from __future__ import annotations

import glob
import gzip
import json
import logging
import os
import re

from ..framework import flags as _flags
from . import flightrec as _flightrec
from .telemetry import PEAK_FLOPS, peak_flops_per_device

__all__ = [
    "PEAK_BW", "peak_bw_per_device", "parse_hlo", "op_table",
    "build_report", "load_trace_op_times", "register_provider",
    "unregister_provider", "collect_reports", "register_owner",
    "unregister_owner", "buffer_census", "hbm_stats", "is_oom",
    "oom_postmortem", "install_oom_hook", "chrome_document", "reset",
]

logger = logging.getLogger("paddle_tpu.monitor")

# Per-chip HBM bandwidth (bytes/s) by device kind, the roofline's other
# axis (PEAK_FLOPS in telemetry.py is the first).  The "cpu" entry is
# NOMINAL, like its PEAK_FLOPS counterpart: CPU-smoke classifications
# are comparable run-over-run, not absolute.
PEAK_BW = {
    "v2": 700e9, "v3": 900e9, "v4": 1228e9,
    "v5 lite": 819e9, "v5e": 819e9, "v5p": 2765e9, "v5": 2765e9,
    "v6 lite": 1640e9, "v6e": 1640e9,
    "cpu": 5e10,
}


def peak_bw_per_device(device=None) -> float:
    """HBM bytes/s for one device: FLAGS_device_peak_bw when set, else
    the longest device-kind match in PEAK_BW, else the v4 figure
    (mirrors telemetry.peak_flops_per_device)."""
    override = float(_flags.flag("FLAGS_device_peak_bw") or 0.0)
    if override > 0:
        return override
    import jax

    d = device if device is not None else jax.devices()[0]
    kind = (getattr(d, "device_kind", "") or "").lower()
    for k, v in sorted(PEAK_BW.items(), key=lambda kv: -len(kv[0])):
        if k in kind:
            return v
    return 1228e9


# ---------------------------------------------------------------------------
# HLO text parsing + analytic per-op costs
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "s8": 1, "u2": 1, "u4": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "token": 0, "opaque": 0, "tuple": 0,
}

# XLA's HloCostAnalysis buckets: transcendental elementwise ops count in
# 'transcendentals', every other elementwise op is one flop per output
# element, and data movement is bytes only.
_TRANSCENDENTAL = {
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "tanh", "sine", "cosine", "tan", "sqrt", "rsqrt", "cbrt", "power",
    "logistic", "erf", "erf-inv", "atan2",
}
_EW_FLOPS = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "compare", "select", "and", "or", "xor", "not", "negate", "abs",
    "sign", "floor", "ceil", "round-nearest-afz", "round-nearest-even",
    "clamp", "remainder", "convert", "is-finite", "shift-left",
    "shift-right-arithmetic", "shift-right-logical", "popcnt", "clz",
    "real", "imag", "complex", "stochastic-convert", "map",
}
_COLLECTIVES = {
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "ragged-all-to-all",
    "all-reduce-start", "all-reduce-done", "all-gather-start",
    "all-gather-done", "collective-permute-start",
    "collective-permute-done", "send", "send-done", "recv", "recv-done",
}
# no runtime work at all: don't even count bytes
_FREE = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "after-all", "partition-id", "replica-id",
    "domain", "opt-barrier", "optimization-barrier",
    "get-dimension-size", "add-dependency",
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,<=\s]*)\]")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?%([\w.\-]+)\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|[a-z][a-z0-9]*\[[^\]]*\](?:\{[^}]*\})?)"
    r"\s+([a-zA-Z][\w\-]*)\(")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLED_RE = re.compile(
    r"(?:calls|to_apply|body|condition|true_computation|"
    r"false_computation|select|scatter)=%([\w.\-]+)")
_CDIMS_RE = re.compile(r"lhs_contracting_dims=\{([0-9,\s]*)\}")
_DIMLBL_RE = re.compile(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)")


def _shape_stats(text):
    """(elements, bytes) summed over every array shape literal in
    ``text`` — one shape for a plain result type, the components for a
    tuple type or an operand list."""
    elems = by = 0
    for m in _SHAPE_RE.finditer(text):
        dt, dims = m.group(1), m.group(2)
        n = 1
        for d in dims.split(","):
            d = d.strip().lstrip("<=").strip()
            if d:
                n *= int(d)
        elems += n
        by += n * _DTYPE_BYTES.get(dt, 4)
    return elems, by


class _Instr:
    __slots__ = ("name", "shape", "opcode", "args", "attrs")

    def __init__(self, name, shape, opcode, args, attrs):
        self.name = name
        self.shape = shape      # result type text
        self.opcode = opcode
        self.args = args        # operand list text (inside the parens)
        self.attrs = attrs      # everything after the closing paren


def parse_hlo(text: str):
    """Parse HLO module text into ``(computations, entry_name)`` where
    computations maps name -> [_Instr].  Only the structure the cost
    model needs — result/operand shapes, opcode, attributes — no full
    grammar."""
    comps, entry, cur = {}, None, None
    for line in text.splitlines():
        if not line:
            continue
        if not line[0].isspace():
            m = _COMP_RE.match(line)
            if m and line.rstrip().endswith("{"):
                cur = m.group(1)
                comps[cur] = []
                if line.lstrip().startswith("ENTRY"):
                    entry = cur
            continue
        if cur is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, shape, opcode = m.groups()
        # operand list: scan from the opcode's '(' to its matching ')'
        start = m.end()            # index just past the '('
        depth, i = 1, start
        while i < len(line) and depth:
            c = line[i]
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
            i += 1
        comps[cur].append(_Instr(name, shape, opcode,
                                 line[start:i - 1], line[i:]))
    if entry is None:
        raise ValueError("no ENTRY computation in HLO text")
    return comps, entry


def _instr_cost(ins, comps, memo):
    """(flops, transcendentals, bytes) for one instruction, rolling up
    called computations (fusion/call/while once-through, conditional
    max-branch) the way HloCostAnalysis does."""
    op = ins.opcode
    if op in _FREE:
        return 0, 0, 0
    out_elems, out_bytes = _shape_stats(ins.shape)
    in_elems, in_bytes = _shape_stats(ins.args)
    byts = in_bytes + out_bytes
    if op == "fusion" or op == "call":
        called = _CALLED_RE.findall(ins.attrs)
        fl = tr = 0
        for c in called:
            cf, ct, _ = _comp_cost(c, comps, memo)
            fl, tr = fl + cf, tr + ct
        return fl, tr, byts
    if op == "while":
        fl = tr = 0
        for c in _CALLED_RE.findall(ins.attrs):
            cf, ct, cb = _comp_cost(c, comps, memo)
            fl, tr, byts = fl + cf, tr + ct, byts + cb
        return fl, tr, byts
    if op == "conditional":
        best = (0, 0, 0)
        for c in _CALLED_RE.findall(ins.attrs):
            cc = _comp_cost(c, comps, memo)
            if cc[0] + cc[1] > best[0] + best[1]:
                best = cc
        return best[0], best[1], byts
    if op == "dot":
        red = 1
        m = _CDIMS_RE.search(ins.attrs)
        lhs = _SHAPE_RE.search(ins.args)
        if m and lhs:
            dims = [d for d in lhs.group(2).split(",") if d.strip()]
            for ix in m.group(1).split(","):
                ix = ix.strip()
                if ix and int(ix) < len(dims):
                    red *= int(dims[int(ix)].strip())
        return 2 * out_elems * red, 0, byts
    if op == "convolution":
        shapes = _SHAPE_RE.findall(ins.args)
        fl = 2 * out_elems
        if len(shapes) >= 2:
            kdims = [int(d) for d in shapes[1][1].split(",") if d.strip()]
            kelems = 1
            for d in kdims:
                kelems *= d
            m = _DIMLBL_RE.search(ins.attrs)
            ochan = kdims[m.group(2).index("o")] \
                if m and "o" in m.group(2) and kdims else 1
            fl = 2 * out_elems * max(1, kelems // max(1, ochan))
        return fl, 0, byts
    if op in ("reduce", "reduce-window", "select-and-scatter", "scatter"):
        fl = tr = 0
        apps = max(0, in_elems - out_elems)
        called = _CALLED_RE.findall(ins.attrs)
        if called:
            bf, bt, _ = _comp_cost(called[0], comps, memo)
            fl, tr = apps * max(1, bf), apps * bt
        else:
            fl = apps
        return fl, tr, byts
    if op in _COLLECTIVES:
        # host-visible cost is wire bytes, not math
        return 0, 0, byts
    if op in _TRANSCENDENTAL:
        return 0, out_elems, byts
    if op in _EW_FLOPS:
        return out_elems, 0, byts
    if op in ("rng", "rng-bit-generator"):
        return 0, out_elems, byts
    if op == "sort":
        n = max(2, out_elems)
        return int(n * max(1, n.bit_length() - 1)), 0, byts
    # data movement and anything unrecognized (custom-call included):
    # zero math, boundary bytes
    return 0, 0, byts


def _comp_cost(name, comps, memo):
    if name in memo:
        return memo[name]
    memo[name] = (0, 0, 0)     # cycle guard
    fl = tr = by = 0
    for ins in comps.get(name, ()):
        f, t, b = _instr_cost(ins, comps, memo)
        fl, tr, by = fl + f, tr + t, by + b
    memo[name] = (fl, tr, by)
    return memo[name]


def _source_label(attrs: str) -> str:
    m = _OPNAME_RE.search(attrs)
    if not m:
        return ""
    return m.group(1).rsplit("/", 1)[-1]


def load_trace_op_times(trace_dir: str) -> dict:
    """Per-event-name durations from a ``jax.profiler`` capture dir:
    {name: {"total_us": float, "count": int}} summed over every
    ``*.trace.json(.gz)`` under it.  XLA's thunk executor names device
    events after entry HLO instructions, which is the join key the op
    table uses."""
    acc = {}
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        for path in glob.glob(os.path.join(trace_dir, pat),
                              recursive=True):
            try:
                if path.endswith(".gz"):
                    with gzip.open(path, "rt") as fh:
                        doc = json.load(fh)
                else:
                    with open(path) as fh:
                        doc = json.load(fh)
            except (OSError, ValueError):
                continue
            for ev in doc.get("traceEvents", ()):
                if ev.get("ph") != "X" or ev.get("dur") is None:
                    continue
                a = acc.setdefault(ev.get("name") or "", [0.0, 0])
                a[0] += float(ev["dur"])
                a[1] += 1
    return {n: {"total_us": t, "count": c} for n, (t, c) in acc.items()}


def op_table(hlo_text: str, *, peak_flops: float = None,
             peak_bw: float = None, measured_step_ms: float = None,
             trace_times: dict = None, top: int = None) -> dict:
    """Build the per-op attribution table from compiled HLO text.

    Rows carry analytic flops/transcendentals/bytes, a roofline time
    estimate, a measured-or-attributed ``time_ms`` (``time_source`` says
    which: "trace" when the profiler capture covered the op,
    "attributed" when a measured step wall was spread by roofline share,
    "estimated" when neither exists), the achieved fraction of roofline,
    and a compute/memory/collective-bound classification.  Rows beyond
    ``top`` roll up into one ``(other)`` row so summed columns stay
    exact."""
    if peak_flops is None:
        peak_flops = peak_flops_per_device()
    if peak_bw is None:
        peak_bw = peak_bw_per_device()
    if top is None:
        top = int(_flags.flag("FLAGS_perf_ops_top") or 48)
    comps, entry = parse_hlo(hlo_text)
    memo = {}
    ridge = peak_flops / max(1.0, peak_bw)   # flops/byte at the knee
    rows = []
    for ins in comps[entry]:
        fl, tr, by = _instr_cost(ins, comps, memo)
        if fl == 0 and tr == 0 and by == 0:
            continue
        est_ms = max((fl + tr) / peak_flops, by / peak_bw) * 1e3
        intensity = (fl + tr) / by if by else float("inf")
        if ins.opcode in _COLLECTIVES:
            bound = "collective"
        elif intensity >= ridge:
            bound = "compute"
        else:
            bound = "memory"
        rows.append({
            "name": ins.name, "op": ins.opcode,
            "source": _source_label(ins.attrs),
            "flops": int(fl), "transcendentals": int(tr),
            "bytes": int(by), "intensity": round(intensity, 3)
            if intensity != float("inf") else None,
            "bound": bound, "est_ms": est_ms,
        })
    # -- measured-time join -------------------------------------------------
    traced_ms = 0.0
    unmatched = []
    for r in rows:
        tt = (trace_times or {}).get(r["name"])
        if tt and tt["count"]:
            r["time_ms"] = (tt["total_us"] / tt["count"]) / 1e3
            r["time_source"] = "trace"
            traced_ms += r["time_ms"]
        else:
            unmatched.append(r)
    if measured_step_ms and unmatched:
        residual = max(0.0, measured_step_ms - traced_ms)
        est_sum = sum(r["est_ms"] for r in unmatched) or 1.0
        for r in unmatched:
            r["time_ms"] = residual * (r["est_ms"] / est_sum)
            r["time_source"] = "attributed"
    else:
        for r in unmatched:
            r["time_ms"] = r["est_ms"]
            r["time_source"] = "estimated"
    for r in rows:
        r["roofline_frac"] = round(min(1.0, r["est_ms"] / r["time_ms"]), 4) \
            if r["time_ms"] > 0 else None
        r["est_ms"] = round(r["est_ms"], 6)
        r["time_ms"] = round(r["time_ms"], 6)
    rows.sort(key=lambda r: -r["time_ms"])
    totals = {
        "flops": sum(r["flops"] for r in rows),
        "transcendentals": sum(r["transcendentals"] for r in rows),
        "bytes": sum(r["bytes"] for r in rows),
        "time_ms": round(sum(r["time_ms"] for r in rows), 6),
        "n_ops": len(rows),
    }
    if len(rows) > top:
        tail = rows[top:]
        rows = rows[:top]
        rows.append({
            "name": "(other)", "op": "(rollup)",
            "source": f"{len(tail)} smaller ops",
            "flops": sum(r["flops"] for r in tail),
            "transcendentals": sum(r["transcendentals"] for r in tail),
            "bytes": sum(r["bytes"] for r in tail),
            "intensity": None, "bound": "mixed",
            "est_ms": round(sum(r["est_ms"] for r in tail), 6),
            "time_ms": round(sum(r["time_ms"] for r in tail), 6),
            "time_source": "rollup", "roofline_frac": None,
        })
    return {"ops": rows, "totals": totals,
            "step_ms": measured_step_ms,
            "peak_flops": peak_flops, "peak_bw": peak_bw,
            "ridge_intensity": round(ridge, 3)}


def build_report(compiled, *, name: str, cost_analysis: dict = None,
                 measured_step_ms: float = None,
                 trace_dir: str = None) -> dict:
    """Op report for one compiled executable: ``compiled`` is anything
    with ``as_text()`` (a ``jax.stages.Compiled``) or raw HLO text."""
    text = compiled.as_text() if hasattr(compiled, "as_text") \
        else str(compiled)
    trace_times = load_trace_op_times(trace_dir) if trace_dir else None
    tbl = op_table(text, measured_step_ms=measured_step_ms,
                   trace_times=trace_times)
    tbl["name"] = name
    if cost_analysis:
        tbl["xla"] = {k: cost_analysis.get(k) for k in
                      ("flops", "transcendentals", "bytes accessed")
                      if cost_analysis.get(k) is not None}
    return tbl


# ---------------------------------------------------------------------------
# report providers (engines publish, /debug/perf collects)
# ---------------------------------------------------------------------------

_providers: dict = {}


def register_provider(name: str, fn):
    """Publish a zero-arg callable returning an op report under
    ``name`` ("train", "decode", ...).  Re-registering replaces."""
    _providers[name] = fn


def unregister_provider(name: str):
    _providers.pop(name, None)


def collect_reports(names=None) -> dict:
    """{name: report} over registered providers; a provider that raises
    yields {"error": ...} instead of poisoning the endpoint."""
    out = {}
    for name, fn in sorted(_providers.items()):
        if names and name not in names:
            continue
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 - introspection never kills
            out[name] = {"name": name,
                         "error": f"{type(e).__name__}: {e}"}
    return out


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------

_owner_suppliers: dict = {}


def register_owner(tag: str, supplier):
    """Register a zero-arg callable returning a pytree whose leaves are
    the device arrays owned by ``tag`` ("params", "opt_state",
    "kv_pages", ...).  Suppliers are invoked at census time; a raising
    supplier is skipped."""
    _owner_suppliers[tag] = supplier


def unregister_owner(tag: str):
    _owner_suppliers.pop(tag, None)


def hbm_stats() -> list:
    """Per-device PJRT memory stats; empty on backends without them
    (CPU)."""
    import jax

    out = []
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 - backend-dependent surface
            ms = None
        if not ms:
            continue
        out.append({"device": str(d),
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "peak_bytes_in_use":
                        int(ms.get("peak_bytes_in_use", 0)),
                    "bytes_limit": int(ms.get("bytes_limit", 0) or 0)})
    return out


def buffer_census(owners=None, top: int = 64) -> dict:
    """Bucket every live device array by (owner tag, dtype, shape).

    ``owners`` overrides the registered suppliers: a dict or iterable of
    ``(tag, pytree_or_supplier)``.  Arrays no supplier claims are tagged
    ``activations`` (in a training process the unclaimed residue is
    activations, input batches, and XLA temporaries).  ``nbytes`` is the
    logical (global) size of a sharded array."""
    import jax

    if owners is None:
        items = list(_owner_suppliers.items())
    elif isinstance(owners, dict):
        items = list(owners.items())
    else:
        items = list(owners)
    id2tag = {}
    for tag, sup in items:
        try:
            tree = sup() if callable(sup) else sup
            for leaf in jax.tree_util.tree_leaves(tree):
                if hasattr(leaf, "nbytes"):
                    id2tag[id(leaf)] = tag
        except Exception:  # noqa: BLE001 - a dead engine ref is fine
            continue
    buckets, by_tag = {}, {}
    total = count = 0
    for arr in jax.live_arrays():
        try:
            nb = int(arr.nbytes)
            key = (id2tag.get(id(arr), "activations"),
                   str(arr.dtype), tuple(arr.shape))
            # the per-device cost of a GSPMD-sharded array is its
            # largest local shard, not the logical nbytes — this is
            # the number that proves a mesh-sharded table (or ZeRO
            # param) fits where the full array would not
            try:
                shard_nb = max((int(s.data.nbytes)
                                for s in arr.addressable_shards),
                               default=nb)
            except Exception:  # noqa: BLE001 - backend w/o shards API
                shard_nb = nb
        except Exception:  # noqa: BLE001 - deleted mid-iteration
            continue
        b = buckets.get(key)
        if b is None:
            b = buckets[key] = {"tag": key[0], "dtype": key[1],
                                "shape": list(key[2]),
                                "count": 0, "bytes": 0, "shard_bytes": 0}
        b["count"] += 1
        b["bytes"] += nb
        b["shard_bytes"] += shard_nb
        by_tag[key[0]] = by_tag.get(key[0], 0) + nb
        total += nb
        count += 1
    blist = sorted(buckets.values(), key=lambda b: -b["bytes"])
    return {"total_bytes": total, "n_arrays": count, "by_tag": by_tag,
            "buckets": blist[:top], "n_buckets": len(blist),
            "devices": hbm_stats()}


# ---------------------------------------------------------------------------
# OOM postmortem
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "out of memory")


def is_oom(exc) -> bool:
    """True for a PJRT/XLA allocation failure (RESOURCE_EXHAUSTED in
    any spelling the runtime uses)."""
    if exc is None:
        return False
    msg = f"{type(exc).__name__}: {exc}"
    return any(m in msg for m in _OOM_MARKERS)


def _postmortem_payload(exc=None) -> dict:
    payload = {"error": str(exc)[:500] if exc is not None else None}
    try:
        payload["census"] = buffer_census()
    except Exception as e:  # noqa: BLE001 - runtime may be torn down
        payload["census_error"] = f"{type(e).__name__}: {e}"
    try:
        payload["op_reports"] = collect_reports()
    except Exception as e:  # noqa: BLE001
        payload["op_reports_error"] = f"{type(e).__name__}: {e}"
    return payload


def oom_postmortem(exc=None) -> str:
    """Dump census + op reports into the flight recorder under reason
    "oom"; returns the dump path ("" when no recorder is configured).
    Engine threads that CATCH the failure call this directly; uncaught
    failures reach the same payload via the crash-hook enricher."""
    payload = _postmortem_payload(exc)
    _flightrec.record("oom", error=payload.get("error"),
                      total_bytes=payload.get("census", {})
                      .get("total_bytes"))
    return _flightrec.dump("oom", extra={"perf": payload})


def _oom_enricher(exc_type, exc):
    if not is_oom(exc):
        return None
    return {"reason": "oom", "extra": {"perf": _postmortem_payload(exc)}}


def install_oom_hook():
    """Attach the OOM enricher to the flight recorder's crash hook: an
    uncaught RESOURCE_EXHAUSTED turns the crash dump into an "oom" dump
    carrying the buffer census and op reports."""
    _flightrec.add_enricher(_oom_enricher)


# ---------------------------------------------------------------------------
# chrome-trace merge (/debug/perf?format=chrome)
# ---------------------------------------------------------------------------

_DEVICE_PID = 999999   # disjoint from the tracer's os.getpid() span pid


def chrome_document(reports: dict, base: dict = None) -> dict:
    """Merge op-report timelines into a chrome-trace document.  ``base``
    is typically ``tracer.chrome_trace()`` so one perfetto load shows
    request spans and device ops side by side; op rows lay out
    sequentially per report on a synthetic "device ops" process."""
    doc = base if base is not None else {"traceEvents": [],
                                         "displayTimeUnit": "ms"}
    events = doc.setdefault("traceEvents", [])
    events.append({"ph": "M", "pid": _DEVICE_PID, "tid": 0,
                   "name": "process_name",
                   "args": {"name": "device ops"}})
    for tid, (rname, report) in enumerate(sorted(reports.items())):
        events.append({"ph": "M", "pid": _DEVICE_PID, "tid": tid,
                       "name": "thread_name", "args": {"name": rname}})
        cursor = 0.0
        for r in report.get("ops", ()):
            dur = max(0.001, float(r.get("time_ms") or 0.0) * 1e3)
            events.append({
                "ph": "X", "cat": "device", "name": r["name"],
                "ts": round(cursor, 3), "dur": round(dur, 3),
                "pid": _DEVICE_PID, "tid": tid,
                "args": {"op": r.get("op"), "source": r.get("source"),
                         "flops": r.get("flops"),
                         "bytes": r.get("bytes"),
                         "bound": r.get("bound"),
                         "roofline_frac": r.get("roofline_frac"),
                         "time_source": r.get("time_source")}})
            cursor += dur
    return doc


def reset():
    """Test isolation: drop registered providers and owner suppliers."""
    _providers.clear()
    _owner_suppliers.clear()
