"""HTTP surface for the runtime telemetry: /metrics, /healthz, and
on-demand trace capture of a RUNNING training job.

Same dependency-free stdlib-HTTP pattern as serving/server.py (one
`ThreadingHTTPServer`, daemon threads, bounded backlog), but pointed at
the shared `utils.metrics` registry instead of a serving engine:

  GET /metrics          Prometheus text of the attached registry; in
                        federation mode (the launcher) the bodies of
                        every rank's own /metrics are appended, so one
                        scrape describes the whole pod.
  GET /healthz          200 {"status": "ok", ...} with the live step
                        plus version/device identity (framework + jax
                        versions, device kind/count, uptime_s, pid) so a
                        fleet health sweep detects version skew.
  GET /debug/trace?steps=N
                        arms a bounded jax.profiler capture of the next
                        N training steps on the attached TrainTelemetry
                        — the running fit picks it up at its next step
                        boundary, so a stuck or slow production job can
                        be profiled WITHOUT restarting it.  SIGUSR1 is
                        the headless equivalent (telemetry.py).
  GET /debug/spans      finished request/train spans from the process
                        tracer (monitor/tracing.py); `?trace_id=` for
                        one trace, `?limit=N`, `?format=chrome` for a
                        perfetto-loadable chrome-trace document.

The server holds no jax state and never blocks training: arming a trace
is a couple of assignments under a lock; the capture itself runs on the
training thread.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.metrics import default_registry
from . import tracing as _tracing

logger = logging.getLogger("paddle_tpu.monitor")

__all__ = ["MonitorServer", "runtime_health"]

_runtime_identity = None
_identity_lock = threading.Lock()


def runtime_health() -> dict:
    """Version/device identity for /healthz (here AND serving/server.py)
    — the fields a fleet sweep compares to detect version skew.  Device
    enumeration is cached after the first call so scrapes stay cheap,
    and every field degrades to a placeholder rather than failing the
    health check."""
    global _runtime_identity
    if _runtime_identity is None:
        with _identity_lock:
            if _runtime_identity is None:
                ident = {}
                try:
                    from .. import __version__ as _ver
                    ident["version"] = _ver
                except Exception:  # noqa: BLE001
                    ident["version"] = "unknown"
                try:
                    import jax
                    ident["jax_version"] = jax.__version__
                    devs = jax.devices()
                    ident["device_kind"] = devs[0].device_kind \
                        if devs else "none"
                    ident["device_count"] = len(devs)
                except Exception:  # noqa: BLE001 - health must answer
                    ident["jax_version"] = "unavailable"
                    ident["device_kind"] = "unavailable"
                    ident["device_count"] = 0
                _runtime_identity = ident
    out = dict(_runtime_identity)
    out["pid"] = os.getpid()
    return out


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 64


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _send(self, code: int, body: bytes, ctype="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj):
        self._send(code, json.dumps(obj).encode())

    def do_GET(self):  # noqa: N802 - http.server API
        owner = self.server.owner
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path == "/metrics":
            self._send(200, owner.metrics_text().encode(),
                       ctype="text/plain; version=0.0.4")
        elif parsed.path == "/healthz":
            self._send_json(200, owner.health())
        elif parsed.path == "/debug/trace":
            q = urllib.parse.parse_qs(parsed.query)
            try:
                steps = int(q.get("steps", ["0"])[0] or 0)
            except ValueError:
                steps = 0
            if steps <= 0:
                self._send_json(400, {"error": "pass ?steps=N (N >= 1)"})
                return
            telem = owner.telemetry
            if telem is None:
                self._send_json(409, {
                    "error": "no training telemetry attached (is a fit "
                             "running with the monitor enabled?)"})
                return
            tdir = telem.arm_trace(steps)
            self._send_json(200, {"armed_steps": steps, "trace_dir": tdir})
        elif parsed.path == "/debug/perf":
            from . import perf as _perf

            q = urllib.parse.parse_qs(parsed.query)
            names = q.get("name") or None
            reports = _perf.collect_reports(names=names)
            if (q.get("format", [""])[0] or "").lower() == "chrome":
                # one perfetto document: request/fit spans (the
                # tracer's export) + a synthetic "device ops" process
                # carrying each report's op timeline
                self._send_json(200, _perf.chrome_document(
                    reports, base=owner.tracer.chrome_trace()))
                return
            try:
                census = _perf.buffer_census()
            except Exception as e:  # noqa: BLE001 - census is best-effort
                census = {"error": f"{type(e).__name__}: {e}"}
            self._send_json(200, {
                "providers": sorted(reports),
                "reports": reports,
                "census": census,
                "hbm": _perf.hbm_stats()})
        elif parsed.path == "/debug/spans":
            q = urllib.parse.parse_qs(parsed.query)
            trace_id = (q.get("trace_id", [None])[0] or None)
            try:
                limit = int(q.get("limit", ["-1"])[0])
            except ValueError:
                limit = -1
            tracer = owner.tracer
            if (q.get("format", [""])[0] or "").lower() == "chrome":
                self._send_json(200, tracer.chrome_trace(trace_id=trace_id))
                return
            spans = tracer.spans(trace_id=trace_id,
                                 limit=limit if limit >= 0 else None)
            self._send_json(200, {
                "sample_rate": tracer.sample_rate,
                "spans_finished": tracer.spans_finished,
                "count": len(spans),
                "spans": spans})
        else:
            self._send_json(404, {"error": f"no route {parsed.path}"})

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("%s - %s", self.address_string(), fmt % args)


class MonitorServer:
    """Expose a metrics registry (default: the shared process registry)
    over HTTP; optionally attach a `TrainTelemetry` for /debug/trace and
    federate other ranks' /metrics (`federate=[base_url, ...]`).

    `extra_registries` co-exposes additional in-process registries (or
    anything with a ``prometheus_text()``, e.g. a serving engine's
    ServingMetrics / GenerationMetrics) on the same /metrics scrape —
    one monitor port covers training AND serving observability."""

    def __init__(self, registry=None, telemetry=None, host="127.0.0.1",
                 port=0, federate=(), fetch_timeout_s=2.0,
                 extra_registries=(), tracer=None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.telemetry = telemetry
        self._tracer = tracer
        self._host = host
        self._requested_port = int(port)
        self.federate = list(federate)
        self.extra_registries = list(extra_registries)
        self.fetch_timeout_s = fetch_timeout_s
        self._httpd = None
        self._thread = None
        self._started_at = None

    # -- endpoint bodies ---------------------------------------------------
    def metrics_text(self) -> str:
        parts = [self.registry.prometheus_text()]
        parts.extend(r.prometheus_text() for r in self.extra_registries)
        if not self.federate:
            return "".join(parts)
        # fetch every rank CONCURRENTLY: N dead ranks must cost one
        # fetch timeout total, not N of them — a pod scrape that blows
        # the scraper's deadline loses the launcher's own healthy
        # counters too
        import concurrent.futures as _cf

        def fetch(base):
            url = base.rstrip("/") + "/metrics"
            try:
                with urllib.request.urlopen(
                        url, timeout=self.fetch_timeout_s) as r:
                    body = r.read().decode("utf-8", "replace")
                return f"# federated from {url}\n{body}"
            except Exception as e:  # noqa: BLE001 - a dead rank must
                # not take down the pod-level scrape (lazy get-or-create:
                # `federate` may be assigned after construction)
                self.registry.counter(
                    "paddle_monitor_federation_errors_total",
                    "rank /metrics fetches that failed during "
                    "federation").inc()
                return (f"# federated from {url}: FETCH FAILED "
                        f"({type(e).__name__})\n")

        with _cf.ThreadPoolExecutor(
                max_workers=min(16, len(self.federate))) as ex:
            parts.extend(ex.map(fetch, list(self.federate)))
        return "".join(parts)

    def health(self) -> dict:
        out = {"status": "ok",
               "uptime_s": round(time.monotonic() - self._started_at, 1)
               if self._started_at else 0.0}
        out.update(runtime_health())
        t = self.telemetry
        if t is not None:
            out["step"] = t.g_step.get()
            out["trace_pending"] = t.trace_pending
        return out

    @property
    def tracer(self):
        """The span tracer /debug/spans queries (default: the process
        tracer, resolved lazily so flag changes before first use win)."""
        return self._tracer if self._tracer is not None \
            else _tracing.default_tracer()

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd \
            else self._requested_port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self) -> "MonitorServer":
        self._httpd = _HTTPServer((self._host, self._requested_port),
                                  _Handler)
        self._httpd.owner = self
        self._started_at = time.monotonic()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1}, daemon=True,
            name="paddle-monitor-http")
        self._thread.start()
        logger.info("monitor serving on %s (/metrics /healthz "
                    "/debug/trace /debug/spans)", self.url)
        return self

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False
