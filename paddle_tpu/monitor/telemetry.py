"""Training telemetry: per-step metrics, JSONL event log, MFU/memory
meters, and bounded on-demand jax.profiler trace capture.

Reference parity: the platform observability layer of the source stack —
platform/profiler.* RecordEvent scopes + DeviceTracer + tools/timeline.py
(PAPER.md layer 1) — rebuilt as one runtime surface: `Model.fit`
instruments every step through a `TrainTelemetry`, which writes

  * the shared `utils.metrics.default_registry()` (scraped over HTTP by
    `monitor.MonitorServer` at /metrics, federated across ranks by the
    launcher), and
  * a rotating append-only JSONL event log under `FLAGS_telemetry_dir`
    (one line per step window, safe to `tail -f`; schema in README
    "Observability").

MFU comes from XLA's own cost model: the engine's `lower_step()` gives
the compiled train step's PER-DEVICE flops (the same numbers the dp
scaling tests assert on), divided by measured step wall time and the
device's peak FLOP/s from `PEAK_FLOPS` (overridable via
`FLAGS_device_peak_flops`).  Memory comes from the PJRT device's
`memory_stats()` — gracefully None on backends that lack it (CPU).

Trace capture is ARMED (from /debug/trace?steps=N, SIGUSR1, or
`arm_trace()`) and then EXECUTED on the training thread at the next step
boundary — `jax.profiler.start_trace` must run on the thread that
dispatches the computation, and a bounded step count guarantees the
capture ends even on a job nobody is watching.  That is what makes a
stuck or slow production fit profile-able without restarting it.

Everything here is jax-free except the trace start/stop and the
memory-stats read, both of which run on the training thread; metric
increments from other threads (checkpoint writer, HTTP handlers) are
pure-python registry work under the registry lock.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time

from ..framework import flags as _flags
from ..utils.metrics import default_registry

logger = logging.getLogger("paddle_tpu.monitor")

__all__ = ["PEAK_FLOPS", "peak_flops_per_device", "device_memory_stats",
           "TrainTelemetry", "JsonlWriter", "install_sigusr1"]

# Per-chip peak FLOP/s by device kind (bf16 systolic peak for TPU
# generations — the BASELINE.md table bench.py uses); the "cpu" entry is
# a NOMINAL figure so CPU smoke runs report a nonzero, comparable-run-
# over-run MFU instead of dividing by zero — absolute CPU MFU is not
# meaningful and README says so.
PEAK_FLOPS = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12, "v5": 459e12,
    "v6 lite": 918e12, "v6e": 918e12,
    "cpu": 1e11,
}


def peak_flops_per_device(device=None) -> float:
    """Peak FLOP/s for one device: FLAGS_device_peak_flops when set,
    else the longest device-kind match in PEAK_FLOPS, else the v4
    figure (same default as bench.py)."""
    override = float(_flags.flag("FLAGS_device_peak_flops") or 0.0)
    if override > 0:
        return override
    import jax

    d = device if device is not None else jax.devices()[0]
    kind = (getattr(d, "device_kind", "") or "").lower()
    for k, v in sorted(PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if k in kind:
            return v
    return 275e12


def device_memory_stats(device=None):
    """{"bytes_in_use": int, "peak_bytes_in_use": int} from the PJRT
    device, or None on backends without memory stats (CPU) — callers
    must treat None as "meter unavailable", not zero."""
    import jax

    try:
        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats()
    except Exception:  # noqa: BLE001 - a meter, never a crash
        return None
    if not stats:
        return None
    out = {}
    if "bytes_in_use" in stats:
        out["bytes_in_use"] = int(stats["bytes_in_use"])
    if "peak_bytes_in_use" in stats:
        out["peak_bytes_in_use"] = int(stats["peak_bytes_in_use"])
    return out or None


class JsonlWriter:
    """Append-only JSONL event log with size-based rotation.

    One `write(record)` = one flushed line, so `tail -f events.jsonl`
    sees complete records.  When the live file exceeds `rotate_bytes`
    it is renamed to `events.jsonl.<n>` (monotonically increasing) and a
    fresh file opened; at most `keep` rotated segments are retained
    (oldest pruned) so a long job's log is bounded."""

    def __init__(self, directory: str, base: str = "events.jsonl",
                 rotate_mb: float = 64.0, keep: int = 4):
        self.directory = directory
        self.base = base
        self.rotate_bytes = max(4096, int(rotate_mb * 1024 * 1024))
        self.keep = keep
        self._lock = threading.Lock()
        self._fh = None
        os.makedirs(directory, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.directory, self.base)

    def _open(self):
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")

    def _rotated(self):
        pre = self.base + "."
        out = []
        for n in os.listdir(self.directory):
            if n.startswith(pre) and n[len(pre):].isdigit():
                out.append(int(n[len(pre):]))
        return sorted(out)

    def _rotate_locked(self):
        self._fh.close()
        self._fh = None
        nums = self._rotated()
        nxt = (nums[-1] + 1) if nums else 1
        os.rename(self.path, f"{self.path}.{nxt}")
        for old in nums[:max(0, len(nums) + 1 - self.keep)]:
            try:
                os.remove(f"{self.path}.{old}")
            except OSError:
                pass
        self._open()

    def write(self, record: dict):
        line = json.dumps(record, separators=(",", ":"),
                          default=_json_default)
        with self._lock:
            self._open()
            self._fh.write(line + "\n")
            self._fh.flush()
            if self._fh.tell() >= self.rotate_bytes:
                self._rotate_locked()

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def _json_default(o):
    try:
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
    except Exception:  # noqa: BLE001
        pass
    return str(o)


class TrainTelemetry:
    """One training job's telemetry stream: registry gauges + JSONL
    events + bounded trace capture.

    `Model.fit` drives it:
      on_fit_begin(meta)      → "fit_begin" event, compile-event counter
      poll_trace()            every step (training thread): start/stop an
                              armed jax.profiler capture
      step_mark()             every step: per-step wall time into the
                              step-time histogram/reservoir (first step —
                              the compile — is recorded as a gauge, not
                              in the histogram)
      window(...)             at log_freq boundaries / epoch ends: phase
                              deltas, samples/s, MFU, memory → gauges +
                              one JSONL line
      ckpt_stall(ms)          checkpoint-induced training-thread stall
      on_fit_end(summary)     → "fit_end" event

    All methods are cheap when nothing fired; the per-step cost with no
    armed trace is two attribute reads and one perf_counter call."""

    def __init__(self, telemetry_dir: str = None, registry=None,
                 rotate_mb: float = None, job: str = "train"):
        self.registry = registry if registry is not None \
            else default_registry()
        self.job = job
        rotate_mb = rotate_mb if rotate_mb is not None else \
            float(_flags.flag("FLAGS_telemetry_rotate_mb") or 64.0)
        self.writer = (JsonlWriter(telemetry_dir, rotate_mb=rotate_mb)
                       if telemetry_dir else None)
        self.telemetry_dir = telemetry_dir
        reg = self.registry
        self.g_mfu = reg.gauge(
            "paddle_train_mfu", "model FLOPs utilization of the train "
            "step (XLA cost-analysis flops / wall / device peak)")
        self.g_samples = reg.gauge(
            "paddle_train_samples_per_sec",
            "training throughput over the last step window")
        self.g_loss = reg.gauge("paddle_train_loss",
                                "last drained training loss")
        self.g_lr = reg.gauge("paddle_train_lr", "current learning rate")
        self.g_step = reg.gauge("paddle_train_step",
                                "global fit iteration counter")
        self.g_epoch = reg.gauge("paddle_train_epoch", "current epoch")
        self.g_first_step_ms = reg.gauge(
            "paddle_train_first_step_ms",
            "wall time of the first dispatched step (compile + warmup)")
        self.g_mem_peak = reg.gauge(
            "paddle_train_device_mem_peak_mb",
            "device peak bytes in use (MB); 0 when the backend has no "
            "memory stats")
        self.g_mem_use = reg.gauge(
            "paddle_train_device_mem_in_use_mb",
            "device bytes in use (MB); 0 when the backend has no "
            "memory stats")
        self.g_hbm_in_use = reg.gauge(
            "paddle_hbm_in_use_bytes",
            "device bytes in use at the last per-step sample (PJRT "
            "memory_stats); 0 when the backend has no memory stats")
        self.g_hbm_watermark = reg.gauge(
            "paddle_hbm_watermark_bytes",
            "high-watermark of device peak bytes in use across the "
            "whole run (sampled every step on the training thread)")
        self._hbm_watermark = 0
        self._hbm_unavailable = False
        self.h_step = reg.histogram(
            "paddle_train_step_ms", "per-step wall time (training-thread "
            "enqueue-to-enqueue; device execution overlaps under the "
            "async engine)",
            [1, 2, 5, 10, 20, 50, 100, 250, 500, 1000, 5000, 30000])
        self.r_step = reg.reservoir("paddle_train_step_ms", size=4096)
        reg.gauge("paddle_train_step_time_p50_ms",
                  "per-step wall time p50 over the recent window",
                  fn=lambda: self.r_step.quantile_locked(0.50))
        reg.gauge("paddle_train_step_time_p99_ms",
                  "per-step wall time p99 over the recent window",
                  fn=lambda: self.r_step.quantile_locked(0.99))
        self.h_phase = {
            name: reg.histogram(
                f"paddle_train_{name}_ms",
                f"per-step mean '{name}' phase time per window (from "
                "StepTimers)", [0.1, 0.5, 1, 2, 5, 10, 20, 50, 100, 500,
                                1000])
            for name in ("data", "dispatch", "sync")}
        self.c_compiles = reg.counter(
            "paddle_train_compile_events_total",
            "jitted train-step (re)builds — cache misses of the "
            "engine's step cache")
        self.c_donation_fallback = reg.counter(
            "paddle_train_donation_fallbacks_total",
            "steps where XLA declined to consume a donated buffer "
            "(counted from jax's donation warnings)")
        self.c_windows = reg.counter(
            "paddle_train_windows_total", "telemetry step windows emitted")
        self.c_traces = reg.counter(
            "paddle_train_traces_total",
            "completed on-demand jax.profiler captures")
        self.h_ckpt_stall = reg.histogram(
            "paddle_ckpt_step_stall_ms",
            "training-thread stall per checkpoint save (host snapshot + "
            "submit/flush)", [1, 5, 10, 25, 50, 100, 250, 500, 1000,
                              5000, 30000])
        # trace arming: mutated from signal handlers / HTTP threads,
        # consumed on the training thread.  _signal_armed is the
        # SIGNAL-SAFE mailbox: a handler may interrupt the training
        # thread INSIDE a _trace_lock critical section, so the handler
        # must never touch the lock (or logging) — it writes one int,
        # and poll_trace converts it to a real arm on the next step
        self._signal_armed = 0
        self._trace_lock = threading.Lock()
        self._armed_steps = 0
        self._trace_steps_left = 0
        self._trace_active = False
        self._trace_dir = None
        self._last_trace_dir = None
        # window bookkeeping (training thread only)
        self._flops_per_step = None
        self._flops_resolved = False
        self._peak_flops = None
        self._last_mark = None
        self._steps_marked = 0

    # -- events ------------------------------------------------------------
    def _emit(self, event: str, **fields):
        if self.writer is None:
            return
        rec = {"ts": round(time.time(), 3), "event": event, "job": self.job}
        rec.update(fields)
        try:
            self.writer.write(rec)
        except OSError as e:
            # the event log is a meter: a full disk must not kill the fit
            logger.warning("telemetry event log write failed: %s", e)

    def on_fit_begin(self, meta: dict = None, compiled: bool = False):
        if compiled:
            self.c_compiles.inc()
        self._last_mark = None
        self._steps_marked = 0
        # each fit re-resolves its own step flops (a different model or
        # mesh changes the program behind the MFU gauge)
        self._flops_per_step = None
        self._flops_resolved = False
        self._emit("fit_begin", **(meta or {}))

    def on_fit_end(self, summary: dict = None):
        self._emit("fit_end", **(summary or {}))

    # -- MFU ---------------------------------------------------------------
    def set_flops_per_step(self, flops: float, peak: float = None):
        """Per-DEVICE flops of one compiled train step (engine
        `lower_step().compile().cost_analysis()` — per-device for SPMD
        modules) against the per-device peak."""
        self._flops_per_step = float(flops) if flops else None
        self._flops_resolved = True
        self._peak_flops = peak if peak is not None \
            else peak_flops_per_device()

    def ensure_flops(self, cost_fn):
        """Resolve flops-per-step ONCE per fit from a `lambda:
        engine.step_cost_analysis(...)` thunk (cached on the engine, so
        repeat fits of the same model don't re-lower).  Any failure
        downgrades the MFU gauge to 0 instead of breaking training."""
        if self._flops_resolved:
            return
        self._flops_resolved = True  # one attempt per fit, success or not
        try:
            ca = cost_fn() or {}
            self.set_flops_per_step(float(ca.get("flops", 0.0)) or None)
        except Exception as e:  # noqa: BLE001 - a meter, never a crash
            logger.warning("telemetry: step cost analysis failed (%s: %s) "
                           "— MFU gauge disabled for this fit",
                           type(e).__name__, e)
            self._flops_per_step = None
        if self._peak_flops is None:
            self._peak_flops = peak_flops_per_device()

    @property
    def flops_per_step(self):
        return self._flops_per_step

    # -- per-step hooks (training thread) ----------------------------------
    def mark_start(self):
        """Anchor the step clock at the START of the first dispatch
        (idempotent): without it the interval containing the jit
        compile — the one `paddle_train_first_step_ms` exists for —
        would be discarded because there is no earlier mark."""
        if self._last_mark is None:
            self._last_mark = time.perf_counter()

    def sample_hbm(self):
        """Per-step HBM watermark sample (training thread): one local
        PJRT memory_stats read — no device sync.  Backends without
        stats (CPU) disable the sampler after the first None so the hot
        loop doesn't pay the probe every step."""
        if self._hbm_unavailable:
            return
        mem = device_memory_stats()
        if mem is None:
            self._hbm_unavailable = True
            return
        self.g_hbm_in_use.set(int(mem.get("bytes_in_use", 0)))
        peak = int(mem.get("peak_bytes_in_use", 0))
        if peak > self._hbm_watermark:
            self._hbm_watermark = peak
            self.g_hbm_watermark.set(peak)

    def step_mark(self):
        now = time.perf_counter()
        self.sample_hbm()
        if self._last_mark is not None:
            dt_ms = (now - self._last_mark) * 1e3
            self._steps_marked += 1
            if self._steps_marked == 1:
                # first dispatched step = compile + warmup: a gauge, so
                # one 4-second compile doesn't own the p99 forever
                self.g_first_step_ms.set(round(dt_ms, 3))
            else:
                with self.registry._lock:
                    self.h_step._observe_locked(dt_ms)
                self.r_step.observe(dt_ms)
        else:
            # direct caller without mark_start: nothing to measure yet
            self._steps_marked += 1
        self._last_mark = now

    def request_trace_signal(self, steps: int):
        """ASYNC-SIGNAL-SAFE trace request (the SIGUSR1 handler): one
        int assignment, no lock, no logging — the handler can interrupt
        the training thread inside _trace_lock, where arm_trace would
        self-deadlock."""
        self._signal_armed = max(1, int(steps))

    def poll_trace(self):
        """Start/advance/stop an armed capture; called at each step
        boundary ON THE TRAINING THREAD (jax.profiler must be driven
        from the dispatching thread).  A few attribute reads when
        idle."""
        if self._signal_armed:
            steps, self._signal_armed = self._signal_armed, 0
            tdir = self.arm_trace(steps)
            logger.warning("SIGUSR1: armed a %d-step trace capture -> %s",
                           steps, tdir)
        if not self._armed_steps and not self._trace_active:
            return
        with self._trace_lock:
            armed, active = self._armed_steps, self._trace_active
            if armed and not active:
                self._armed_steps = 0
                self._trace_steps_left = armed
                tdir = self._trace_dir or self._default_trace_dir()
                try:
                    import jax

                    jax.profiler.start_trace(tdir)
                except Exception as e:  # noqa: BLE001 - meter
                    logger.error("trace capture failed to start: %s", e)
                    return
                self._trace_active = True
                self._last_trace_dir = tdir
                logger.info("trace capture ARMED for %d steps -> %s",
                            armed, tdir)
                self._emit("trace_begin", steps=armed, trace_dir=tdir)
                return
            if active:
                self._trace_steps_left -= 1
                if self._trace_steps_left <= 0:
                    self._stop_trace_locked()

    def _stop_trace_locked(self):
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            logger.error("trace capture failed to stop: %s", e)
        self._trace_active = False
        self.c_traces.inc()
        logger.info("trace capture complete -> %s", self._last_trace_dir)
        self._emit("trace_end", trace_dir=self._last_trace_dir)

    def finish_trace(self):
        """Stop a still-active capture at fit exit (a trace armed for
        more steps than remained must still produce a valid artifact)."""
        with self._trace_lock:
            if self._trace_active:
                self._stop_trace_locked()

    def _default_trace_dir(self):
        base = self.telemetry_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "paddle_tpu_telemetry")
        return os.path.join(base, "traces",
                            time.strftime("%Y%m%d-%H%M%S"))

    def arm_trace(self, steps: int, trace_dir: str = None) -> str:
        """Arm a bounded capture of the next `steps` training steps.
        Safe from any thread AND from a signal handler (one lock-free
        assignment would suffice; the lock orders racing armers).
        Returns the directory the trace will land in."""
        steps = max(1, int(steps))
        with self._trace_lock:
            tdir = trace_dir or self._default_trace_dir()
            if self._trace_active:
                # already capturing: extend, keep the live dir
                self._trace_steps_left = max(self._trace_steps_left, steps)
                return self._last_trace_dir
            self._trace_dir = tdir
            self._armed_steps = steps
            return tdir

    @property
    def trace_pending(self) -> bool:
        return bool(self._armed_steps or self._trace_active
                    or self._signal_armed)

    @property
    def last_trace_dir(self):
        return self._last_trace_dir

    # -- window emission (training thread) ---------------------------------
    def window(self, *, step: int, epoch: int, steps: int, wall_s: float,
               batch_size: int, loss=None, lr=None, timers=None,
               phase_deltas: dict = None, extra: dict = None) -> dict:
        """Close one step window: update every gauge/histogram and emit
        one JSONL line.  `phase_deltas` is {phase: (d_total_s, d_count)}
        from StepTimers since the previous window."""
        steps = max(1, int(steps))
        wall_s = max(1e-9, float(wall_s))
        sps = steps * batch_size / wall_s
        step_ms = wall_s / steps * 1e3
        mfu = 0.0
        if self._flops_per_step and self._peak_flops:
            mfu = self._flops_per_step * steps / wall_s / self._peak_flops
        mem = device_memory_stats()
        rec = {
            "step": int(step), "epoch": int(epoch), "steps": steps,
            "samples_per_sec": round(sps, 3),
            "step_ms_mean": round(step_ms, 4),
            # 9 digits: a CPU-smoke MFU against the nominal peak is
            # ~1e-6 and must not round to a dead gauge
            "mfu": round(mfu, 9),
        }
        if loss is not None:
            rec["loss"] = float(loss)
            self.g_loss.set(float(loss))
        if lr is not None:
            rec["lr"] = float(lr)
            self.g_lr.set(float(lr))
        phase_ms = {}
        if phase_deltas:
            for name, (d_total, d_count) in phase_deltas.items():
                if d_count <= 0:
                    continue
                mean_ms = d_total / d_count * 1e3
                phase_ms[name] = round(mean_ms, 4)
                h = self.h_phase.get(name)
                if h is not None:
                    h.observe(mean_ms)
        if phase_ms:
            rec["phase_ms"] = phase_ms
        if self._flops_per_step:
            rec["flops_per_step"] = self._flops_per_step
        if mem is not None:
            mb = 1.0 / (1024 * 1024)
            rec["mem"] = {
                "in_use_mb": round(mem.get("bytes_in_use", 0) * mb, 2),
                "peak_mb": round(mem.get("peak_bytes_in_use", 0) * mb, 2)}
            self.g_mem_use.set(rec["mem"]["in_use_mb"])
            self.g_mem_peak.set(rec["mem"]["peak_mb"])
        else:
            rec["mem"] = None
        if extra:
            rec.update(extra)
        self.g_mfu.set(round(mfu, 9))
        self.g_samples.set(round(sps, 3))
        self.g_step.set(int(step))
        self.g_epoch.set(int(epoch))
        self.c_windows.inc()
        self._emit("window", **rec)
        return rec

    def ckpt_stall(self, ms: float):
        self.h_ckpt_stall.observe(ms)
        self._emit("ckpt", stall_ms=round(ms, 3))

    def install_warning_hook(self):
        """Count donation-fallback warnings (jax's "Some donated buffers
        were not usable") without touching the engine's hot path: wrap
        `warnings.showwarning` for the duration of a fit.

        The default warning filter deduplicates repeats from the same
        code location BEFORE showwarning runs — a chronic every-step
        fallback would count 1.  So an "always" filter is pushed for
        donation warnings while the hook is installed; the hook itself
        de-duplicates the CONSOLE output back to once per fit, so the
        counter is exact without turning a chronic fallback into ten
        thousand log lines.  Returns a restore() callable; chains to the
        previous hook so user-installed hooks keep firing."""
        import warnings

        prev = warnings.showwarning
        prev_filters = list(warnings.filters)
        warnings.filterwarnings("always", message=".*[Dd]onated")
        counter = self.c_donation_fallback
        printed = [0]

        def hook(message, category, filename, lineno, file=None,
                 line=None):
            if "donated" in str(message).lower():
                counter.inc()
                printed[0] += 1
                if printed[0] > 1:
                    return  # counted; don't spam the console
            prev(message, category, filename, lineno, file, line)

        warnings.showwarning = hook

        def restore():
            if warnings.showwarning is hook:
                warnings.showwarning = prev
            warnings.filters[:] = prev_filters

        return restore

    def close(self):
        self.finish_trace()
        if self.writer is not None:
            self.writer.close()


def install_sigusr1(telemetry: TrainTelemetry, steps: int = None):
    """SIGUSR1 → arm a bounded trace capture (the headless equivalent of
    /debug/trace?steps=N).  Main-thread only (signal.signal raises
    elsewhere — returns None then).  Returns a restore() callable."""
    steps = steps if steps is not None else \
        int(_flags.flag("FLAGS_trace_steps") or 3)

    def _handler(signum, frame):
        # handler body must be async-signal-safe: no locks, no logging
        # (either could be held by the very frame this interrupts)
        telemetry.request_trace_signal(steps)

    try:
        prev = signal.signal(signal.SIGUSR1, _handler)
    except (ValueError, OSError, AttributeError):
        return None

    def restore():
        try:
            signal.signal(signal.SIGUSR1, prev)
        except (ValueError, OSError):
            pass

    return restore
