# pta: jax-free
"""Request-scoped span tracing: trace/span ids, W3C traceparent
propagation, probabilistic head sampling, chrome-trace export.

Reference parity: paddle/fluid/platform/device_tracer.* + the
tools/timeline.py chrome-trace writer — Fluid recorded kernel-level
causality into a protobuf and rendered it offline; here the unit of
causality is a *request* (serving) or a *fit/epoch/step* (training), the
recorder is a bounded in-process ring, and the export is the same
chrome://tracing / perfetto JSON the timeline tool produced.

Dependency-free by design (stdlib only, no jax, no OpenTelemetry): a
`Span` is a dict-sized object stamped with `time.monotonic()`; ending it
appends one summary dict to the tracer's ring and notifies listeners
(the crash flight recorder subscribes).  Sampling is *head* sampling
decided from the trace_id itself —

    int(trace_id[:8], 16) < FLAGS_trace_sample_rate * 2**32

— so every process that sees the same trace_id (client, server, engine)
independently reaches the same keep/drop decision without coordination.
Unsampled requests cost one shared no-op `NullSpan`; with
`FLAGS_trace_sample_rate 0` the tracer is fully disabled.

Context propagates over HTTP via the W3C `traceparent` header
(https://www.w3.org/TR/trace-context/):

    00-<32 hex trace_id>-<16 hex parent span_id>-<2 hex flags>

with flag bit 0x01 = sampled.  serving/client.py injects it on every
predict/generate; serving/server.py adopts it so the server-side span
tree joins the caller's trace.  `MonitorServer /debug/spans` queries the
ring (`?trace_id=`, `?format=chrome` for a perfetto-loadable document).
"""
from __future__ import annotations

import collections
import os
import threading
import time

from ..framework import flags as _flags

__all__ = ["Span", "NullSpan", "Tracer", "default_tracer", "reset",
           "format_traceparent", "parse_traceparent", "sample_decision"]

_MAX_EVENTS_PER_SPAN = 512  # per-token decode events stay bounded


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def format_traceparent(trace_id: str, span_id: str, sampled: bool) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def parse_traceparent(header):
    """-> (trace_id, parent_span_id, sampled) or None on any malformed
    input (a bad header must never fail the request it rode in on)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags_hex = parts[:4]
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(version, 16)
        int(trace_id, 16)
        int(span_id, 16)
        flag_bits = int(flags_hex, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(flag_bits & 0x01)


def sample_decision(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling from the id: every participant that
    derives the decision from the same trace_id agrees."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    try:
        return int(trace_id[:8], 16) < rate * 0x100000000
    except (ValueError, TypeError):
        return False


class Span:
    """One timed operation in a trace.  Context-manager; `child()` for
    sub-operations, `event()` for point-in-time annotations (per-token
    marks), `end()` exactly once (idempotent)."""

    __slots__ = ("_tracer", "trace_id", "span_id", "parent_id", "name",
                 "attrs", "events", "t0_wall", "t0", "dur_ms", "tid",
                 "_ended")

    sampled = True

    def __init__(self, tracer, name, trace_id, parent_id=None, attrs=None):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = _new_id(8)
        self.parent_id = parent_id
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.events = []          # (name, t_ms offset, attrs-or-None)
        self.t0_wall = time.time()
        self.t0 = time.perf_counter()
        self.dur_ms = 0.0
        self.tid = threading.get_ident()
        self._ended = False

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id, True)

    def set_attr(self, key, value):
        self.attrs[key] = value

    def event(self, name, **attrs):
        if len(self.events) < _MAX_EVENTS_PER_SPAN:
            self.events.append(
                (name, (time.perf_counter() - self.t0) * 1e3,
                 attrs or None))
        else:
            self.attrs["events_dropped"] = \
                self.attrs.get("events_dropped", 0) + 1

    def child(self, name, **attrs) -> "Span":
        return Span(self._tracer, name, self.trace_id,
                    parent_id=self.span_id, attrs=attrs or None)

    def end(self, status: str = None):
        if self._ended:
            return
        self._ended = True
        self.dur_ms = (time.perf_counter() - self.t0) * 1e3
        if status is not None:
            self.attrs["status"] = status
        self._tracer._record(self)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end(status="error" if exc_type is not None else None)
        return False


class NullSpan:
    """No-op span with the full Span surface, returned for unsampled
    traces.  Carries the (trace_id, span_id) pair when the trace exists
    but was head-sampled OUT, so the unsampled `traceparent` still
    propagates the consistent drop decision downstream."""

    __slots__ = ("trace_id", "span_id")

    sampled = False
    dur_ms = 0.0

    def __init__(self, trace_id=None, span_id=None):
        self.trace_id = trace_id
        self.span_id = span_id

    @property
    def traceparent(self):
        if self.trace_id is None:
            return None
        return format_traceparent(self.trace_id,
                                  self.span_id or "f" * 16, False)

    def set_attr(self, key, value):
        pass

    def event(self, name, **attrs):
        pass

    def child(self, name, **attrs) -> "NullSpan":
        return self

    def end(self, status: str = None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = NullSpan()


class Tracer:
    """Head-sampling span recorder over a bounded ring of finished
    spans.  Thread-safe: spans start/end on HTTP handler threads, the
    batcher, the decode loop, and the training thread concurrently."""

    def __init__(self, sample_rate: float = None, max_spans: int = None):
        if sample_rate is None:
            sample_rate = float(
                _flags.flag("FLAGS_trace_sample_rate", 0.01) or 0.0)
        if max_spans is None:
            max_spans = int(
                _flags.flag("FLAGS_trace_buffer_spans", 2048) or 2048)
        self.sample_rate = float(sample_rate)
        self.max_spans = max(1, int(max_spans))
        self._spans = collections.deque(maxlen=self.max_spans)
        self._lock = threading.Lock()
        self._listeners = []
        self.spans_finished = 0

    @property
    def enabled(self) -> bool:
        return self.sample_rate > 0.0

    def add_listener(self, fn):
        """fn(span_dict) on every recorded span end (flight recorder)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def start_span(self, name, *, traceparent=None, parent=None,
                   attrs=None, sampled=None):
        """Root-or-child span entry point.

        `parent=` an in-process Span/NullSpan continues it directly;
        `traceparent=` adopts a remote context (its sampled flag WINS —
        the caller already decided); otherwise a fresh trace is started
        and head-sampled, or forced by `sampled=True` (training fits:
        few per process, always worth recording when tracing is on).
        """
        if not self.enabled:
            return _NULL
        if parent is not None:
            if not parent.sampled:
                return parent if isinstance(parent, NullSpan) else _NULL
            return Span(self, name, parent.trace_id,
                        parent_id=parent.span_id, attrs=attrs)
        ctx = parse_traceparent(traceparent) if traceparent else None
        if ctx is not None:
            trace_id, parent_id, keep = ctx
            if not keep:
                return NullSpan(trace_id, parent_id)
            return Span(self, name, trace_id, parent_id=parent_id,
                        attrs=attrs)
        trace_id = _new_id(16)
        if sampled is None:
            sampled = sample_decision(trace_id, self.sample_rate)
        if not sampled:
            return NullSpan(trace_id, _new_id(8))
        return Span(self, name, trace_id, attrs=attrs)

    # -- recording ---------------------------------------------------------
    def _record(self, span: Span):
        rec = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "ts_ms": round(span.t0_wall * 1e3, 3),
            "dur_ms": round(span.dur_ms, 3),
            "tid": span.tid,
            "attrs": span.attrs,
            "events": [
                {"name": n, "t_ms": round(t, 3), **(a or {})}
                for n, t, a in span.events],
        }
        with self._lock:
            self._spans.append(rec)
            self.spans_finished += 1
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 - a broken listener must
                pass           # never fail the traced operation

    # -- queries -----------------------------------------------------------
    def spans(self, trace_id: str = None, limit: int = None) -> list[dict]:
        """Finished spans, oldest first; optionally one trace only."""
        with self._lock:
            out = list(self._spans)
        if trace_id:
            out = [s for s in out if s["trace_id"] == trace_id]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def trace_ids(self) -> list[str]:
        """Distinct trace ids present in the ring, oldest first."""
        seen = []
        for s in self.spans():
            if s["trace_id"] not in seen:
                seen.append(s["trace_id"])
        return seen

    def clear(self):
        with self._lock:
            self._spans.clear()

    def chrome_trace(self, trace_id: str = None) -> dict:
        """Perfetto/chrome://tracing-loadable document: one complete "X"
        event per span (ts/dur in microseconds), one instant "i" event
        per span event."""
        pid = os.getpid()
        events = []
        for s in self.spans(trace_id=trace_id):
            ts_us = s["ts_ms"] * 1e3
            args = dict(s["attrs"])
            args.update({"trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"]})
            events.append({"ph": "X", "cat": "paddle", "name": s["name"],
                           "ts": ts_us, "dur": s["dur_ms"] * 1e3,
                           "pid": pid, "tid": s["tid"], "args": args})
            for ev in s["events"]:
                events.append({
                    "ph": "i", "cat": "paddle", "s": "t",
                    "name": f'{s["name"]}/{ev["name"]}',
                    "ts": ts_us + ev["t_ms"] * 1e3,
                    "pid": pid, "tid": s["tid"]})
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "metadata": {"tracer": "paddle_tpu.monitor.tracing",
                             "sample_rate": self.sample_rate}}


_default: Tracer | None = None
_default_lock = threading.Lock()


def default_tracer() -> Tracer:
    """Process-wide tracer, built lazily from FLAGS_trace_sample_rate /
    FLAGS_trace_buffer_spans at first use."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Tracer()
    return _default


def reset():
    """Drop the process singleton so the next default_tracer() re-reads
    flags (tests)."""
    global _default
    with _default_lock:
        _default = None
