"""paddle.nn — layers + functional.

Reference parity: python/paddle/nn/__init__.py (2.0 API surface).
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .functional import extension  # noqa: F401 — ref nn/__init__.py:19
from .layer import common  # noqa: F401 — ref nn/__init__.py:20
from .utils import weight_norm_hook  # noqa: F401 — ref nn/__init__.py:22
from .utils import remove_weight_norm, weight_norm  # noqa: F401
from .layer_base import Layer, Parameter, ParamAttr, functional_call, state_pytrees  # noqa: F401
from .layer.container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layer.common import (  # noqa: F401
    AlphaDropout,
    Bilinear,
    CosineSimilarity,
    Dropout,
    Dropout2D,
    Dropout3D,
    Embedding,
    Flatten,
    Identity,
    Linear,
    Pad1D,
    Pad2D,
    Pad3D,
    PixelShuffle,
    Unfold,
    Upsample,
    UpsamplingBilinear2D,
    UpsamplingNearest2D,
)
from .layer.activation import (  # noqa: F401
    CELU,
    ELU,
    GELU,
    GLU,
    Hardshrink,
    Hardsigmoid,
    Hardswish,
    Hardtanh,
    LeakyReLU,
    LogSigmoid,
    LogSoftmax,
    Maxout,
    Mish,
    PReLU,
    ReLU,
    ReLU6,
    SELU,
    Sigmoid,
    Silu,
    Softmax,
    Softplus,
    Softshrink,
    Softsign,
    Swish,
    Tanh,
    Tanhshrink,
    ThresholdedReLU,
)
from .layer.conv import (  # noqa: F401
    Conv1D,
    Conv1DTranspose,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    SpectralNorm,
    SyncBatchNorm,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool1D,
    AdaptiveAvgPool2D,
    AdaptiveMaxPool2D,
    AvgPool1D,
    AvgPool2D,
    AvgPool3D,
    MaxPool1D,
    MaxPool2D,
    MaxPool3D,
)
from .layer.loss import (  # noqa: F401
    BCELoss,
    BCEWithLogitsLoss,
    CosineEmbeddingLoss,
    CrossEntropyLoss,
    CTCLoss,
    HingeEmbeddingLoss,
    KLDivLoss,
    L1Loss,
    MarginRankingLoss,
    MSELoss,
    NLLLoss,
    SmoothL1Loss,
)
from .layer.rnn import (  # noqa: F401
    GRU,
    LSTM,
    BiRNN,
    GRUCell,
    LSTMCell,
    RNN,
    SimpleRNN,
    SimpleRNNCell,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401


# --------------------------------------------------------------------------
# reference paddle.nn surface completion (round-4)
# --------------------------------------------------------------------------
from .layer import conv, loss, rnn as _rnn_mod  # noqa: F401,E402
rnn = _rnn_mod
from .layer.rnn import _RNNCellBase as RNNCellBase  # noqa: F401,E402
from ..static.nn import cond, while_loop  # noqa: F401,E402


def Input(shape=None, dtype="float32", name=None):
    """paddle.nn.Input -> an InputSpec for to_static signatures (the
    static-graph placeholder form is paddle.static.data)."""
    from ..jit import InputSpec

    return InputSpec(shape=shape, dtype=dtype, name=name)


def crf_decoding(*args, **kwargs):
    from . import functional as _F

    return _F.crf_decoding(*args, **kwargs)


def ctc_greedy_decoder(*args, **kwargs):
    from . import functional as _F

    return _F.ctc_greedy_decoder(*args, **kwargs)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        from . import functional as _F

        return _F.adaptive_avg_pool3d(x, self._os)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        from . import functional as _F

        return _F.adaptive_max_pool1d(x, self._os)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        from . import functional as _F

        return _F.adaptive_max_pool3d(x, self._os)


class PairwiseDistance(Layer):
    """||x - y||_p along the last axis (nn/layer/distance.py)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.eps, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from .. import tensor_ops as T

        d = T.add(T.subtract(x, y), T.full([1], self.eps, "float32"))
        return T.norm(d, p=self.p, axis=-1, keepdim=self.keepdim)


class Decoder:
    """Seq2seq decoder contract (paddle.nn.decode.Decoder):
    initialize() -> (inputs, states, finished); step() -> (outputs,
    states, next_inputs, finished)."""

    def initialize(self, inits):
        raise NotImplementedError("subclass Decoder and implement "
                                  "initialize()")

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError("subclass Decoder and implement step()")

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """Beam-search decoding over an RNN cell
    (paddle.nn.BeamSearchDecoder re-designed on text.beam_search_step):
    embedding_fn maps token ids to cell inputs, output_fn maps cell
    outputs to vocab logits."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start, self.end = int(start_token), int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn or (lambda ids: ids)
        self.output_fn = output_fn or (lambda x: x)


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Run a BeamSearchDecoder to completion (paddle.nn.dynamic_decode):
    returns (token ids [B, beam, T], final scores [B, beam]).  Eager
    host loop — the jit form is a user-side lax.scan over
    text.beam_search_step."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor, unwrap
    from ..text import beam_search_decode, beam_search_step

    cell, W = decoder.cell, decoder.beam_size
    state0 = inits
    if state0 is None:
        raise ValueError("dynamic_decode needs the encoder final state "
                         "as `inits`")
    B = unwrap(state0[0] if isinstance(state0, (tuple, list))
               else state0).shape[0]

    def tile(s):
        if isinstance(s, (tuple, list)):
            return type(s)(tile(x) for x in s)
        v = unwrap(s)
        return Tensor(jnp.repeat(v, W, axis=0))

    states = tile(state0)
    ids = Tensor(jnp.full((B * W,), decoder.start, jnp.int32))
    scores = jnp.where(jnp.arange(W)[None, :] == 0, 0.0, -1e9)
    scores = Tensor(jnp.broadcast_to(scores, (B, W)).astype(jnp.float32))
    fin = Tensor(jnp.zeros((B, W), bool))
    step_ids, step_parents = [], []
    for t in range(max_step_num):
        out, states = cell(decoder.embedding_fn(ids), states)
        logits = decoder.output_fn(out)
        V = unwrap(logits).shape[-1]
        logp = jax.nn.log_softmax(unwrap(logits), -1)
        sel_ids, parents, scores = beam_search_step(
            Tensor(logp.reshape(B, W, V)), scores, W,
            end_token=decoder.end, finished=fin)
        step_ids.append(unwrap(sel_ids))
        step_parents.append(unwrap(parents))
        # reorder states along the beam axis by parent
        flat_parent = (jnp.arange(B)[:, None] * W
                       + unwrap(parents)).reshape(-1)

        def reorder(s):
            if isinstance(s, (tuple, list)):
                return type(s)(reorder(x) for x in s)
            return Tensor(unwrap(s)[flat_parent])

        states = reorder(states)
        ids = Tensor(unwrap(sel_ids).reshape(-1).astype(jnp.int32))
        fin = Tensor(unwrap(fin)[
            jnp.arange(B)[:, None], unwrap(parents)]
            | (unwrap(sel_ids) == decoder.end))
        if bool(np.asarray(unwrap(fin)).all()):
            break
    seqs, final_scores = beam_search_decode(
        Tensor(jnp.stack(step_ids)), Tensor(jnp.stack(step_parents)),
        scores)
    return seqs, final_scores


class _FluidEraStub:
    _msg = ""

    def __init__(self, *a, **k):
        raise NotImplementedError(self._msg)


class DynamicRNN(_FluidEraStub):
    _msg = ("DynamicRNN is a fluid LoD program builder; on TPU write the "
            "recurrence with nn.LSTM/GRU or lax.scan over padded "
            "sequences (COVERAGE.md, text.sequence)")


class StaticRNN(_FluidEraStub):
    _msg = ("StaticRNN is a fluid program builder; on TPU write the "
            "recurrence with nn.LSTM/GRU or lax.scan (COVERAGE.md)")


class HSigmoidLoss(_FluidEraStub):
    _msg = ("hierarchical sigmoid needs a host-side Huffman tree; use "
            "full softmax cross_entropy (COVERAGE.md non-goal)")


class NCELoss(_FluidEraStub):
    _msg = ("NCE needs a host-side sampling table; use sampled softmax "
            "composed from multinomial + cross_entropy (COVERAGE.md "
            "non-goal)")


class TreeConv(_FluidEraStub):
    _msg = ("TreeConv is a PS-era recommender op (COVERAGE.md non-goal)")


from ..vision import ops as vision  # noqa: F401,E402  (paddle.nn.vision)


def __getattr__(name):
    # lazy: sparse imports nn (layer_base, initializer), so an eager
    # import here would cycle.  nn.ShardedEmbeddingTable is the
    # Embedding-compatible face of the sparse subsystem.
    if name == "ShardedEmbeddingTable":
        from ..sparse.table import ShardedEmbeddingTable
        return ShardedEmbeddingTable
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
