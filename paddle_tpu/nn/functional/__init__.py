"""nn.functional — stateless NN ops.

Reference parity: python/paddle/nn/functional/* over the C++ op zoo
(activation ops, conv2d/cudnn conv, pool2d, batch/layer/group norm, dropout,
softmax_with_cross_entropy_op.cc:301, lookup_table_v2 embedding, ...).

TPU-native: each op is a jnp/lax lowering; convs and matmuls lower to XLA
convolution/dot (MXU); fused paths (flash attention, fused LN/softmax-xent)
swap in Pallas kernels via paddle_tpu.ops when FLAGS_use_pallas_kernels is on.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as _random
from ...framework.flags import flag
from ...tensor import Tensor, apply, unwrap
from ... import tensor_ops as T

pad = T.pad  # re-export (paddle.nn.functional.pad)


# ---------------------------------------------------------------------------
# activations (operators/activation_op.cc family)
# ---------------------------------------------------------------------------
def relu(x, name=None):
    return apply(jax.nn.relu, x)


def relu_(x, name=None):
    out = relu(x)
    x._value = out.value
    return x


def relu6(x, name=None):
    return apply(lambda v: jnp.clip(v, 0.0, 6.0), x)


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, x)


def tanh(x, name=None):
    return apply(jnp.tanh, x)


def gelu(x, approximate=False, name=None):
    return apply(lambda v: jax.nn.gelu(v, approximate=approximate), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda v: jax.nn.leaky_relu(v, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.elu(v, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x)


def celu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.celu(v, alpha), x)


def silu(x, name=None):
    return apply(jax.nn.silu, x)


def swish(x, name=None):
    return apply(jax.nn.silu, x)


def mish(x, name=None):
    return apply(lambda v: v * jnp.tanh(jax.nn.softplus(v)), x)


def hardswish(x, name=None):
    return apply(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply(lambda v: jnp.clip(v, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.sign(v) * jnp.maximum(jnp.abs(v) - threshold, 0.0), x)


def tanhshrink(x, name=None):
    return apply(lambda v: v - jnp.tanh(v), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda v: jnp.where(v * beta > threshold, v,
                                     jax.nn.softplus(v * beta) / beta), x)


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, x)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(lambda v: jnp.where(v > threshold, v, 0.0), x)


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(v, w):
        if w.size == 1:
            ww = w.reshape(())
        else:
            shape = [1] * v.ndim
            c_axis = 1 if data_format[1] == "C" else v.ndim - 1
            shape[c_axis] = w.size
            ww = w.reshape(shape)
        return jnp.where(v > 0, v, ww * v)
    return apply(f, x, weight)


def softmax(x, axis=-1, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            from ...framework.dtype import convert_dtype
            v = v.astype(convert_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)
    return apply(f, x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply(lambda v: jax.nn.log_softmax(v, axis=axis), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = _random.split_key()

    def f(v):
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, v.shape, v.dtype, 1e-20, 1.0)))
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis) \
                if hasattr(jnp, "put_along_axis") else \
                jax.nn.one_hot(jnp.squeeze(idx, axis), y.shape[axis], axis=axis,
                               dtype=y.dtype)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return apply(f, x)


def glu(x, axis=-1, name=None):
    return apply(lambda v: jax.nn.glu(v, axis=axis), x)


def maxout(x, groups, axis=1, name=None):
    def f(v):
        c = v.shape[axis]
        new_shape = list(v.shape)
        new_shape[axis] = c // groups
        new_shape.insert(axis + 1, groups)
        return jnp.max(v.reshape(new_shape), axis=axis + 1)
    return apply(f, x)


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------
def _static_dim(t, axis):
    """Best-effort static dim of a Tensor/array/Variable (None when
    unknown/symbolic) for friendly pre-dispatch shape errors."""
    shape = getattr(t, "shape", None)
    if not shape:
        return None
    try:
        d = shape[axis]
    except (IndexError, TypeError):
        return None
    return int(d) if isinstance(d, (int,)) and d >= 0 else None


def _check_dim(got, want, op, what):
    """Raise a named ValueError instead of letting XLA emit a raw
    dot/conv dimension error (known UX gap: wrong-shape inputs used to
    surface as compiler messages)."""
    if got is not None and want is not None and got != want:
        raise ValueError(f"{op}: {what}: got {got}, expected {want}")


def linear(x, weight, bias=None, name=None):
    """paddle convention: weight shape [in_features, out_features]."""
    from ...amp import white_cast

    _check_dim(_static_dim(x, -1), _static_dim(weight, 0), "linear",
               "input last dim vs weight in_features")

    if bias is None:
        return apply(lambda v, w: jnp.matmul(*white_cast(v, w)), x, weight)

    def f(v, w, b):
        v, w = white_cast(v, w)
        return v @ w + b.astype(v.dtype)

    return apply(f, x, weight, bias)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    dt = str(getattr(x, "dtype", ""))
    if dt.startswith("float") or dt.startswith("bfloat"):
        raise TypeError(
            f"embedding: ids must be an integer tensor, got dtype {dt}")

    def f(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply(f, x, weight)


def one_hot(x, num_classes, name=None):
    return apply(lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32), x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k
    args = (label,) if prior_dist is None else (label, prior_dist)
    return apply(f, *args)


# ---------------------------------------------------------------------------
# convolution (conv2d + cudnn variants → XLA conv_general_dilated)
# ---------------------------------------------------------------------------
def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(u) for u in v)


def _conv_padding(padding, nsp, strides=None):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * nsp
    padding = list(padding)
    if all(isinstance(p, (list, tuple)) for p in padding):
        # possibly includes batch/channel dims (paddle allows 4-elem pair list)
        pairs = [tuple(p) for p in padding]
        if len(pairs) == nsp + 2:
            pairs = pairs[2:]
        return pairs
    if len(padding) == nsp:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * nsp:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nsp)]
    raise ValueError(f"bad padding {padding}")


def _dimension_numbers(nsp, channel_last):
    sp = "DHW"[-nsp:]
    if channel_last:
        return (f"N{sp}C", f"{sp}IO"[::1].replace(sp, sp) if False else f"O{sp}I"[0:0] or f"{sp}",)  # unreachable
    return None


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, nsp,
          transpose=False, output_padding=0):
    channel_last = data_format[-1] == "C"
    # friendly channel check for all six conv entry points: paddle
    # weight layouts are [out_c, in_c/groups, *k] (conv) and
    # [in_c, out_c/groups, *k] (transpose)
    win = _static_dim(weight, 0 if transpose else 1)
    want = None if win is None else (win if transpose else win * groups)
    _check_dim(_static_dim(x, -1 if channel_last else 1), want,
               f"conv{nsp}d{'_transpose' if transpose else ''}",
               f"input channels ({data_format}) vs weight layout")
    stride = _norm_tuple(stride, nsp)
    dilation = _norm_tuple(dilation, nsp)
    pad_spec = _conv_padding(padding, nsp)
    sp = "DHW"[3 - nsp:]
    if channel_last:
        lhs_spec = "N" + sp + "C"
        out_spec = "N" + sp + "C"
    else:
        lhs_spec = "NC" + sp
        out_spec = "NC" + sp
    rhs_spec = "OI" + sp  # paddle weight layout: [out_c, in_c/groups, *k]

    def f(v, w, *b):
        from ...amp import white_cast

        v, w = white_cast(v, w)
        if b:
            b = (b[0].astype(v.dtype),)
        if transpose:
            # paddle conv_transpose weight: [in_c, out_c/groups, *k].
            # Express as a fractionally-strided conv: dilate the input by
            # `stride`, swap the kernel's I/O dims and flip it spatially
            # (the gradient-of-conv identity).
            k = w.shape[2:]
            if isinstance(pad_spec, str):
                pads = pad_spec
            else:
                # output = (in-1)*s - 2p + k (+ output_padding)
                pads = [(d * (kk - 1) - p[0], d * (kk - 1) - p[1] + op)
                        for kk, p, d, op in zip(
                            k, pad_spec, dilation,
                            _norm_tuple(output_padding, nsp))]
            wt = jnp.swapaxes(w, 0, 1) if groups == 1 else _group_swap(w, groups)
            wt = jnp.flip(wt, axis=tuple(range(2, wt.ndim)))
            out = jax.lax.conv_general_dilated(
                v, wt,
                window_strides=(1,) * nsp,
                padding=pads,
                lhs_dilation=stride,
                rhs_dilation=dilation,
                dimension_numbers=(lhs_spec, rhs_spec, out_spec),
                feature_group_count=groups,
            )
        else:
            out = jax.lax.conv_general_dilated(
                v, w,
                window_strides=stride,
                padding=pad_spec,
                rhs_dilation=dilation,
                dimension_numbers=(lhs_spec, rhs_spec, out_spec),
                feature_group_count=groups,
            )
        if b:
            bshape = [1] * out.ndim
            bshape[out_spec.index("C")] = b[0].size
            out = out + b[0].reshape(bshape)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(f, *args)


def _group_swap(w, groups):
    # [in_c, out_c/groups, *k] -> grouped OIHW-transposed layout
    ic, ocg = w.shape[0], w.shape[1]
    k = w.shape[2:]
    w = w.reshape((groups, ic // groups, ocg) + k)
    w = jnp.swapaxes(w, 1, 2)
    return w.reshape((groups * ocg, ic // groups) + k)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, fmt, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCL",
                     name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, fmt, 1,
                 transpose=True, output_padding=output_padding)


def _transpose_output_padding(x, weight, stride, padding, dilation,
                              output_size, nsp, data_format):
    """Requested output_size -> per-dim output_padding (conv_transpose
    semantics: out = (in-1)*s - 2*p + d*(k-1) + 1 + output_padding)."""
    if output_size is None:
        return 0
    sizes = ([output_size] * nsp if isinstance(output_size, int)
             else list(output_size))
    channel_last = data_format[-1] == "C"
    xshape = list(getattr(x, "shape", None) or np.shape(unwrap(x)))
    wshape = list(getattr(weight, "shape", None) or np.shape(unwrap(weight)))
    in_sp = xshape[1:1 + nsp] if channel_last else xshape[2:2 + nsp]
    k_sp = wshape[-nsp:]
    s = [stride] * nsp if isinstance(stride, int) else list(stride)
    p = [padding] * nsp if isinstance(padding, int) else list(padding)
    d = [dilation] * nsp if isinstance(dilation, int) else list(dilation)
    out_pad = []
    for i in range(nsp):
        base = (in_sp[i] - 1) * s[i] - 2 * p[i] + d[i] * (k_sp[i] - 1) + 1
        op_i = int(sizes[i]) - base
        if not 0 <= op_i < s[i] + 1:
            raise ValueError(
                f"output_size[{i}]={sizes[i]} unreachable: base deconv "
                f"size is {base}, output_padding must be in [0, {s[i]}]")
        out_pad.append(op_i)
    return out_pad


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCHW",
                     name=None):
    if output_size is not None:
        output_padding = _transpose_output_padding(
            x, weight, stride, padding, dilation, output_size, 2,
            data_format)
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format,
                 2, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCDHW",
                     name=None):
    if output_size is not None:
        output_padding = _transpose_output_padding(
            x, weight, stride, padding, dilation, output_size, 3,
            data_format)
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format,
                 3, transpose=True, output_padding=output_padding)


# ---------------------------------------------------------------------------
# pooling (pool2d op → lax.reduce_window)
# ---------------------------------------------------------------------------
def _pool(x, kernel, stride, padding, nsp, data_format, op, ceil_mode=False,
          include_pad=False, count_include_pad=True):
    channel_last = data_format[-1] == "C"
    kernel = _norm_tuple(kernel, nsp)
    stride = _norm_tuple(stride if stride is not None else kernel, nsp)
    pad_spec = _conv_padding(padding, nsp)

    def f(v):
        if channel_last:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = [(0, 0)] + (pad_spec if isinstance(pad_spec, list)
                               else [(0, 0)] * nsp) + [(0, 0)] \
                if not isinstance(pad_spec, str) else pad_spec
        else:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = [(0, 0), (0, 0)] + (pad_spec if isinstance(pad_spec, list)
                                       else [(0, 0)] * nsp) \
                if not isinstance(pad_spec, str) else pad_spec
        if isinstance(pads, str):
            pads_resolved = jax.lax.padtype_to_pads(v.shape, window, strides,
                                                    pads)
        else:
            pads_resolved = pads
        if ceil_mode and not isinstance(pads_resolved, str):
            # extend right pads so ceil-divided windows fit
            pads_resolved = list(pads_resolved)
            sp_offset = 1 if channel_last else 2
            for i in range(nsp):
                d = sp_offset + i
                size = v.shape[d] + pads_resolved[d][0] + pads_resolved[d][1]
                rem = (size - kernel[i]) % stride[i]
                if rem:
                    pads_resolved[d] = (pads_resolved[d][0],
                                        pads_resolved[d][1] + stride[i] - rem)
        if op == "max":
            # init must carry the operand dtype as a CONCRETE numpy scalar:
            # a python -inf becomes f64 under x64 (CPU) and poisons the
            # graph, while a jax array init breaks reduce_window transpose
            init = (np.dtype(v.dtype).type(-np.inf)
                    if jnp.issubdtype(v.dtype, jnp.floating)
                    else np.dtype(v.dtype).type(jnp.iinfo(v.dtype).min))
            return jax.lax.reduce_window(v, init, jax.lax.max, window, strides,
                                         pads_resolved)
        # avg
        ones = jnp.ones_like(v)
        s = jax.lax.reduce_window(v, np.dtype(v.dtype).type(0), jax.lax.add,
                                  window, strides, pads_resolved)
        if count_include_pad:
            denom = float(np.prod(kernel))
            return s / denom
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                    pads_resolved)
        return s / cnt

    return apply(f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool(x, kernel_size, stride, padding, 1, fmt, "max", ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "max", ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool(x, kernel_size, stride, padding, 1, fmt, "avg", ceil_mode,
                 count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg",
                 ceil_mode, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg",
                 ceil_mode, count_include_pad=not exclusive)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_hw = _norm_tuple(output_size, 2)

    def f(v):
        channel_last = data_format[-1] == "C"
        h_ax, w_ax = (1, 2) if channel_last else (2, 3)
        H, W = v.shape[h_ax], v.shape[w_ax]
        oh, ow = out_hw
        if H % oh == 0 and W % ow == 0:
            kh, kw = H // oh, W // ow
            window = [1, 1, 1, 1]
            window[h_ax], window[w_ax] = kh, kw
            s = jax.lax.reduce_window(v, 0.0, jax.lax.add, tuple(window),
                                      tuple(window), "VALID")
            return s / (kh * kw)
        # general: mean over computed bins (static shapes)
        hi = [(int(math.floor(i * H / oh)), int(math.ceil((i + 1) * H / oh)))
              for i in range(oh)]
        wi = [(int(math.floor(j * W / ow)), int(math.ceil((j + 1) * W / ow)))
              for j in range(ow)]
        rows = []
        for (h0, h1) in hi:
            cols = []
            for (w0, w1) in wi:
                sl = [slice(None)] * v.ndim
                sl[h_ax], sl[w_ax] = slice(h0, h1), slice(w0, w1)
                cols.append(jnp.mean(v[tuple(sl)], axis=(h_ax, w_ax),
                                     keepdims=True))
            rows.append(jnp.concatenate(cols, axis=w_ax))
        return jnp.concatenate(rows, axis=h_ax)

    return apply(f, x)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_hw = _norm_tuple(output_size, 2)

    def f(v):
        H, W = v.shape[2], v.shape[3]
        oh, ow = out_hw
        kh, kw = H // oh, W // ow
        return jax.lax.reduce_window(v, np.dtype(v.dtype).type(-np.inf),
                                     jax.lax.max,
                                     (1, 1, kh, kw), (1, 1, kh, kw), "VALID")
    return apply(f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    def f(v):
        L = v.shape[-1]
        o = output_size if isinstance(output_size, int) else output_size[0]
        k = L // o
        return jax.lax.reduce_window(v, 0.0, jax.lax.add, (1, 1, k), (1, 1, k),
                                     "VALID") / k
    return apply(f, x)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    ns = (normalized_shape,) if isinstance(normalized_shape, int) \
        else tuple(normalized_shape)
    naxes = len(ns)
    for i, want in enumerate(ns):
        _check_dim(_static_dim(x, -naxes + i), int(want), "layer_norm",
                   f"trailing dim {-naxes + i} vs normalized_shape")

    from ...ops import fused as _fused
    if (flag("FLAGS_use_pallas_kernels") and naxes == 1 and weight is not None
            and bias is not None):
        return _fused.layer_norm(x, weight, bias, epsilon)

    def f(v, *wb):
        axes = tuple(range(v.ndim - naxes, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mean), axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [a for a in (x, weight, bias) if a is not None]
    return apply(f, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    channel_last = data_format[-1] == "C" and len(data_format) > 2

    def f(v, rm, rv, *wb):
        c_ax = v.ndim - 1 if channel_last else (1 if v.ndim > 1 else 0)
        axes = tuple(i for i in range(v.ndim) if i != c_ax)
        use_batch = training and not use_global_stats
        if use_batch:
            # E[x^2] - E[x]^2 instead of jnp.var's two dependent passes:
            # both reductions read x once, so XLA multi-output-fuses them
            # into a single sweep over the (usually conv-output) operand —
            # BN train is HBM-bound and this drops one full pass
            mean = jnp.mean(v, axis=axes)
            mean_sq = jnp.mean(jnp.square(v), axis=axes)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        else:
            mean, var = rm, rv
        shape = [1] * v.ndim
        shape[c_ax] = v.shape[c_ax]
        out = (v - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [a for a in (x, running_mean, running_var, weight, bias)
            if a is not None]
    out = apply(f, *args)

    # running-stat update (mirrors batch_norm_op: stats updated in forward)
    if training and not use_global_stats:
        v = unwrap(x)
        c_ax = v.ndim - 1 if channel_last else (1 if v.ndim > 1 else 0)
        axes = tuple(i for i in range(v.ndim) if i != c_ax)
        with jax.ensure_compile_time_eval() if False else _noop_ctx():
            bm = jnp.mean(v, axis=axes)
            n = np.prod([v.shape[a] for a in axes])
            # same sum/sum-sq formulation as the normalize path so the
            # whole stats computation CSEs with it inside one jit
            bv = jnp.maximum(jnp.mean(jnp.square(v), axis=axes)
                             - jnp.square(bm), 0.0) * (n / max(n - 1, 1))
            running_mean.set_value(running_mean.value * momentum + bm * (1 - momentum))
            running_var.set_value(running_var.value * momentum + bv * (1 - momentum))
    return out


import contextlib as _ctxlib


def _noop_ctx():
    return _ctxlib.nullcontext()


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def f(v, *wb):
        axes = tuple(range(2, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [a for a in (x, weight, bias) if a is not None]
    return apply(f, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format[-1] == "C" and len(data_format) > 2

    def f(v, *wb):
        if channel_last:
            v_ = jnp.moveaxis(v, -1, 1)
        else:
            v_ = v
        N, C = v_.shape[0], v_.shape[1]
        g = v_.reshape((N, num_groups, C // num_groups) + v_.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v_.shape)
        shape = [1, C] + [1] * (v_.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = [a for a in (x, weight, bias) if a is not None]
    return apply(f, *args)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply(lambda v: v / jnp.maximum(
        jnp.linalg.norm(v, ord=p, axis=axis, keepdims=True), epsilon), x)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(v):
        sq = jnp.square(v)
        half = size // 2
        c_ax = 1
        pad_width = [(0, 0)] * v.ndim
        pad_width[c_ax] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_width)
        window = [1] * v.ndim
        window[c_ax] = size
        s = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(window),
                                  (1,) * v.ndim, "VALID")
        return v / jnp.power(k + alpha * s, beta)
    return apply(f, x)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = _random.split_key()

    def f(v):
        shape = list(v.shape)
        if axis is not None:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0)
        return jnp.where(keep, v, 0.0)

    return apply(f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = _random.split_key()

    def f(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / math.sqrt((1 - p) * (1 + p * alpha_p ** 2))) if p < 1 else 0.0
        b = -a * alpha_p * p
        return a * jnp.where(keep, v, alpha_p) + b
    return apply(f, x)


# ---------------------------------------------------------------------------
# losses (softmax_with_cross_entropy_op.cc:301 etc.)
# ---------------------------------------------------------------------------
def _reduce_loss(loss_fn_out, reduction):
    if reduction == "mean":
        return T.mean(loss_fn_out)
    if reduction == "sum":
        return T.sum(loss_fn_out)
    return loss_fn_out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    if not soft_label:
        ldt = str(getattr(label, "dtype", ""))
        if ldt.startswith("float") or ldt.startswith("bfloat"):
            raise TypeError(
                "cross_entropy: hard labels must be integer class ids "
                f"(got dtype {ldt}); pass soft_label=True for "
                "probability targets")
    from ...ops import fused as _fused
    if (flag("FLAGS_use_pallas_kernels") and use_softmax and not soft_label
            and weight is None and axis in (-1, None)):
        # routes to ops/fused.softmax_cross_entropy, which on TPU runs the
        # fused Pallas log-softmax+gather kernel (ops/pallas/softmax_xent)
        # and otherwise the stable XLA composite
        raw = _fused.softmax_cross_entropy(input, label, ignore_index)
        return _reduce_loss(raw, reduction) if reduction != "none" else raw

    def f(logits, lbl, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-15, 1.0))
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis)
        else:
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logp.ndim:
                lbl_i = jnp.squeeze(lbl_i, axis)
            picked = jnp.take_along_axis(
                logp, lbl_i[..., None] if axis in (-1, logp.ndim - 1)
                else jnp.expand_dims(lbl_i, axis), axis=axis)
            loss = -jnp.squeeze(picked, axis)
            valid = lbl_i != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if w:
                cw = jnp.take(w[0], jnp.clip(lbl_i, 0, None), axis=0)
                loss = loss * jnp.where(valid, cw, 0.0)
        return loss

    args = [input, label] + ([weight] if weight is not None else [])
    raw = apply(f, *args)
    if reduction == "none":
        return raw
    if reduction == "sum":
        return T.sum(raw)
    if soft_label or (ignore_index == -100 and weight is None):
        return T.mean(raw)

    # mean over valid entries, weighted if a class-weight vector was given
    nd = len(unwrap(input).shape)

    def denom_fn(l, *w):
        li = l.astype(jnp.int32)
        if li.ndim == nd:
            li = jnp.squeeze(li, axis)
        valid = li != ignore_index
        if w:
            cw = jnp.take(w[0], jnp.clip(li, 0, None), axis=0)
            return jnp.sum(jnp.where(valid, cw, 0.0))
        return jnp.sum(valid.astype(jnp.float32))

    denom = apply(denom_fn, label, *([weight] if weight is not None else []))
    return T.sum(raw) / denom


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = T.unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def f(logp, lbl, *w):
        lbl_i = lbl.astype(jnp.int32)
        if logp.ndim > 2:  # [N, C, d1...] form: class axis lives at 1
            logp = jnp.moveaxis(logp, 1, -1)
        ign = lbl_i == ignore_index
        safe = jnp.where(ign, 0, lbl_i)  # gather-safe index for ignored rows
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)
        loss = -jnp.squeeze(picked, -1)
        wt = jnp.take(w[0], safe, axis=0) if w \
            else jnp.ones(loss.shape, logp.dtype)
        wt = jnp.where(ign, 0.0, wt)
        # mask the PRODUCT: an ignored row with -inf log-prob would turn
        # inf * 0 into NaN if only the weight were zeroed
        wl = jnp.where(ign, 0.0, loss * wt)
        if reduction == "mean":
            # the nll_loss contract (reference nll_loss op == torch):
            # mean divides by the TOTAL WEIGHT of non-ignored targets,
            # not the row count; an all-ignored batch is 0/0 = NaN,
            # exactly torch's behavior
            return wl.sum() / wt.sum()
        if reduction == "sum":
            return wl.sum()
        return wl
    args = [input, label] + ([weight] if weight is not None else [])
    return apply(f, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return _reduce_loss(apply(lambda a, b: jnp.square(a - b), input, label),
                        reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _reduce_loss(apply(lambda a, b: jnp.abs(a - b), input, label),
                        reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        return jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta) * delta \
            / delta
    return _reduce_loss(apply(lambda a, b: jnp.where(
        jnp.abs(a - b) < delta, 0.5 * jnp.square(a - b) / delta,
        jnp.abs(a - b) - 0.5 * delta), input, label), reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, l, *w):
        eps = 1e-12
        loss = -(l * jnp.log(jnp.clip(p, eps, None))
                 + (1 - l) * jnp.log(jnp.clip(1 - p, eps, None)))
        if w:
            loss = loss * w[0]
        return loss
    args = [input, label] + ([weight] if weight is not None else [])
    return _reduce_loss(apply(f, *args), reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, l, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        log_sig = jax.nn.log_sigmoid(z)
        log_one_minus = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * l * log_sig + (1 - l) * log_one_minus)
        else:
            loss = -(l * log_sig + (1 - l) * log_one_minus)
        if w is not None:
            loss = loss * w
        return loss
    args = [logit, label] + [a for a in (weight, pos_weight) if a is not None]
    return _reduce_loss(apply(f, *args), reduction)


def kl_div(input, label, reduction="mean", name=None):
    raw = apply(lambda lp, t: t * (jnp.log(jnp.clip(t, 1e-12, None)) - lp),
                input, label)
    if reduction == "batchmean":
        return T.sum(raw) / unwrap(input).shape[0]
    return _reduce_loss(raw, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return _reduce_loss(apply(
        lambda a, b, l: jnp.maximum(0.0, -l * (a - b) + margin),
        input, other, label), reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _reduce_loss(apply(
        lambda a, l: jnp.where(l == 1, a, jnp.maximum(0.0, margin - a)),
        input, label), reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return apply(f, x1, x2)


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    sim = cosine_similarity(input1, input2, axis=1)
    return _reduce_loss(apply(
        lambda s, l: jnp.where(l == 1, 1 - s, jnp.maximum(0.0, s - margin)),
        sim, label), reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, l, *n):
        p = jax.nn.sigmoid(z)
        ce = -(l * jax.nn.log_sigmoid(z) + (1 - l) * jax.nn.log_sigmoid(-z))
        p_t = p * l + (1 - p) * (1 - l)
        a_t = alpha * l + (1 - alpha) * (1 - l)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return loss
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return _reduce_loss(apply(f, *args), reduction)


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard alpha recursion in log space (warpctc analog)."""
    def f(lp, lab, il, ll):
        # lp: [T, B, C] logits; convert to log-probs
        lp = jax.nn.log_softmax(lp, axis=-1)
        Tmax, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = -1e30
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), ext[:, 0]])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(ll > 0, lp[0, jnp.arange(B), ext[:, 1]], neg_inf))

        same = jnp.concatenate(
            [jnp.full((B, 2), False),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a = alpha
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), a[:, :-1]], 1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), a[:, :-2]], 1)
            a2 = jnp.where(same, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(a, a1), a2)
            new = m + jnp.log(jnp.exp(a - m) + jnp.exp(a1 - m)
                              + jnp.exp(a2 - m) + 1e-30)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return new + emit, None

        def scan_body(carry, t):
            alpha = carry
            new, _ = step(alpha, lp[t])
            alpha = jnp.where((t < il)[:, None], new, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, Tmax))
        idx_last = 2 * ll.astype(jnp.int32)
        b_idx = jnp.arange(B)
        final = jnp.logaddexp(
            alpha[b_idx, idx_last],
            jnp.where(ll > 0, alpha[b_idx, jnp.maximum(idx_last - 1, 0)], neg_inf))
        return -final

    raw = apply(f, log_probs, labels, input_lengths, label_lengths)
    if reduction == "mean":
        return T.mean(apply(lambda r, ll: r / jnp.maximum(ll, 1), raw,
                            label_lengths))
    return _reduce_loss(raw, reduction)


# ---------------------------------------------------------------------------
# attention + sequence utilities
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """[B, S, H, D] layout. Uses the Pallas flash-attention kernel on TPU
    when enabled (ops/pallas/flash_attention.py), else an XLA softmax path."""
    from ...ops import fused as _fused
    return _fused.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
        is_causal=is_causal, training=training)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    from ...framework.dtype import convert_dtype
    ml = maxlen

    def f(l):
        m = ml if ml is not None else int(jnp.max(l))
        ar = jnp.arange(m)
        return (ar[None, :] < l[..., None]).astype(convert_dtype(dtype))
    return apply(f, lengths)


# ---------------------------------------------------------------------------
# vision ops
# ---------------------------------------------------------------------------
def _cubic_resize_axis(v, axis, s_out, align_corners):
    """Separable bicubic resize along one axis with the Keys a=-0.75
    kernel — the coefficient the reference kernel (bicubic_interp_op.h
    cubic_convolution1/2) and torch use; jax.image.resize's 'cubic' is
    a=-0.5 and diverges visibly (0.2 abs on unit-normal inputs)."""
    a = -0.75
    s_in = v.shape[axis]
    if s_in == s_out:
        return v
    j = np.arange(s_out, dtype=np.float64)
    if align_corners and s_out > 1:
        src = j * (s_in - 1) / (s_out - 1)
    else:
        src = (j + 0.5) * (s_in / s_out) - 0.5
    f0 = np.floor(src)
    t = src - f0

    def k(d):  # cubic convolution weight at distance |d|
        d = np.abs(d)
        return np.where(
            d <= 1, ((a + 2) * d - (a + 3)) * d * d + 1,
            np.where(d < 2, ((a * d - 5 * a) * d + 8 * a) * d - 4 * a, 0.0))

    taps, weights = [], []
    for off in (-1, 0, 1, 2):
        taps.append(np.clip(f0 + off, 0, s_in - 1).astype(np.int32))
        weights.append(k(t - off))
    out = None
    shape = [1] * v.ndim
    shape[axis] = s_out
    for idx, w in zip(taps, weights):
        piece = jnp.take(v, jnp.asarray(idx), axis=axis) * \
            jnp.asarray(w, v.dtype).reshape(shape)
        out = piece if out is None else out + piece
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    def f(v):
        channel_last = data_format[-1] == "C"
        sp_axes = list(range(1, v.ndim - 1)) if channel_last \
            else list(range(2, v.ndim))
        in_sizes = [v.shape[a] for a in sp_axes]
        if size is not None:
            out_sizes = [int(unwrap(s)) for s in
                         (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(in_sizes)
            out_sizes = [int(s * f_) for s, f_ in zip(in_sizes, sf)]
        new_shape = list(v.shape)
        for a, s in zip(sp_axes, out_sizes):
            new_shape[a] = s
        m = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        if m == "nearest":
            return jax.image.resize(v, new_shape, method="nearest")
        if m == "cubic":
            out = v
            for a_, s_out in zip(sp_axes, out_sizes):
                out = _cubic_resize_axis(out, a_, s_out, align_corners)
            return out
        if align_corners:
            # jax.image.resize has no align_corners; emulate via per-axis map
            out = v
            for a, s_out in zip(sp_axes, out_sizes):
                s_in = out.shape[a]
                if s_out == s_in:
                    continue
                idx = jnp.linspace(0.0, s_in - 1, s_out)
                lo = jnp.floor(idx).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, s_in - 1)
                w = (idx - lo).astype(v.dtype)
                shape = [1] * out.ndim
                shape[a] = s_out
                wv = w.reshape(shape)
                out = jnp.take(out, lo, axis=a) * (1 - wv) + \
                    jnp.take(out, hi, axis=a) * wv
            return out
        return jax.image.resize(v, new_shape, method=m)
    return apply(f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(v):
        N, C, H, W = v.shape
        v = v.reshape(N, C // (r * r), r, r, H, W)
        v = v.transpose(0, 1, 4, 2, 5, 3)
        return v.reshape(N, C // (r * r), H * r, W * r)
    return apply(f, x)


def _unfold_pads(paddings):
    """1/2/4-int padding forms (reference unfold_op): 1 → all sides,
    2 → (ph, pw), 4 → (top, left, bottom, right). Returns ((pt,pb),(pl,pr))."""
    if isinstance(paddings, int):
        return (paddings, paddings), (paddings, paddings)
    p = list(paddings)
    if len(p) == 1:
        return (p[0], p[0]), (p[0], p[0])
    if len(p) == 2:
        return (p[0], p[0]), (p[1], p[1])
    if len(p) == 4:
        return (p[0], p[2]), (p[1], p[3])
    raise ValueError(f"paddings must have 1, 2 or 4 elements, got {p}")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    (pt, pb), (pl, pr) = _unfold_pads(paddings)
    d = _norm_tuple(dilations, 2)

    def f(v):
        N, C, H, W = v.shape
        patches = jax.lax.conv_general_dilated_patches(
            v, k, s, [(pt, pb), (pl, pr)], rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        L = patches.shape[2] * patches.shape[3]
        return patches.reshape(N, C * k[0] * k[1], L)
    return apply(f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im — inverse of unfold (operators/fold_op): x [N, C*kh*kw, L]
    -> [N, C, H, W] with overlapping patches summed (scatter-add via the
    transpose of the patch-extraction convolution)."""
    out = _norm_tuple(output_sizes, 2)
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    (pt, pb), (pl, pr) = _unfold_pads(paddings)
    d = _norm_tuple(dilations, 2)

    def f(v):
        N, CKK, L = v.shape
        C = CKK // (k[0] * k[1])
        oh = (out[0] + pt + pb - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (out[1] + pl + pr - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = v.reshape(N, C, k[0], k[1], oh, ow)
        # scatter-add each kernel tap into the padded output
        acc = jnp.zeros((N, C, out[0] + pt + pb, out[1] + pl + pr),
                        v.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                ys = i * d[0]
                xs = j * d[1]
                acc = acc.at[:, :, ys:ys + oh * s[0]:s[0],
                             xs:xs + ow * s[1]:s[1]].add(cols[:, :, i, j])
        return acc[:, :, pt:pt + out[0], pl:pl + out[1]]

    return apply(f, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Sampling grid from affine matrices (operators/affine_grid_op):
    theta [N,2,3], out_shape [N,C,H,W] -> grid [N,H,W,2] for grid_sample."""
    if hasattr(out_shape, "numpy"):
        out_shape = [int(x) for x in np.asarray(out_shape.numpy())]
    N, C, H, W = (int(x) for x in out_shape)

    def f(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
        else:
            ys = (jnp.arange(H) * 2 + 1) / H - 1
            xs = (jnp.arange(W) * 2 + 1) / W - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # [H,W,3]
        return jnp.einsum("hwk,nik->nhwi", base,
                          th.astype(jnp.float32)).astype(th.dtype)

    return apply(f, theta)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM channel shift along time (operators/temporal_shift_op):
    x [N*T, C, H, W] -> same shape with the first fold of channels shifted
    back one step in time, the second fold forward."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"data_format must be NCHW or NHWC, got {data_format}")

    def f(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        NT, C, H, W = v.shape
        T = seg_num
        B = NT // T
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        v = v.reshape(B, T, C, H, W)
        back = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])],
                               axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]),
                               v[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([back, fwd, v[:, :, c2:]], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply(f, x)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Reference grid_sampler_op semantics (= torch.grid_sample):
    bilinear/nearest modes, zeros/border/reflection padding; nearest
    rounds half-to-even (nearbyint)."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample: unknown mode '{mode}'")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(
            f"grid_sample: unknown padding_mode '{padding_mode}'")

    def f(v, g):
        N, C, H, W = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            ix = (gx + 1) * (W - 1) / 2
            iy = (gy + 1) * (H - 1) / 2
        else:
            ix = ((gx + 1) * W - 1) / 2
            iy = ((gy + 1) * H - 1) / 2

        def reflect(c, size):
            if align_corners:
                if size <= 1:
                    return jnp.zeros_like(c)
                span = 2.0 * (size - 1)
                r = jnp.mod(jnp.abs(c), span)
                return jnp.minimum(r, span - r)
            span = 2.0 * size
            r = jnp.mod(jnp.abs(c + 0.5), span)
            return jnp.minimum(r, span - r) - 0.5

        if padding_mode == "border":
            ix = jnp.clip(ix, 0, W - 1)
            iy = jnp.clip(iy, 0, H - 1)
        elif padding_mode == "reflection":
            ix = jnp.clip(reflect(ix, W), 0, W - 1)
            iy = jnp.clip(reflect(iy, H), 0, H - 1)
        masked = padding_mode == "zeros"

        def sample(img, yy, xx):
            def get(ix_, iy_):
                ic = jnp.clip(ix_, 0, W - 1)
                jc = jnp.clip(iy_, 0, H - 1)
                val = img[:, jc, ic]  # [C, Ho, Wo]
                if masked:
                    inb = (ix_ >= 0) & (ix_ < W) & (iy_ >= 0) & (iy_ < H)
                    val = jnp.where(inb[None], val, 0.0)
                return val

            if mode == "nearest":
                return get(jnp.round(xx).astype(jnp.int32),
                           jnp.round(yy).astype(jnp.int32))
            x0 = jnp.floor(xx).astype(jnp.int32)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = xx - x0
            wy = yy - y0
            return (get(x0, y0) * (1 - wx) * (1 - wy)
                    + get(x1, y0) * wx * (1 - wy)
                    + get(x0, y1) * (1 - wx) * wy
                    + get(x1, y1) * wx * wy)

        out = jax.vmap(sample)(v, iy, ix)
        return out
    return apply(f, x, grid)


# alias namespace used by reference code: paddle.nn.functional.common
def linear_compat(*args, **kwargs):
    return linear(*args, **kwargs)


# --------------------------------------------------------------------------
# op-registry tail (COVERAGE.md round-4): direct functional lowerings of
# the remaining reference kernels
# --------------------------------------------------------------------------

def add_position_encoding(x, alpha=1.0, beta=1.0, name=None):
    """alpha*x + beta*PE (operators/add_position_encoding_op.h): first
    half of the feature dim gets sin(pos/10000^(i/half)), second half
    cos, matching the reference's split layout."""
    def f(v):
        B, T, D = v.shape
        half = D // 2
        pos = jnp.arange(T, dtype=v.dtype)[:, None]
        i = jnp.arange(half, dtype=v.dtype)[None, :]
        div = jnp.power(jnp.asarray(10000.0, v.dtype), i / jnp.maximum(half - 1, 1))
        pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], -1)
        if pe.shape[-1] < D:  # odd feature dim: pad last column
            pe = jnp.pad(pe, ((0, 0), (0, D - pe.shape[-1])))
        return alpha * v + beta * pe[None]
    return apply(f, x)


def bilinear(x1, x2, weight, bias=None, name=None):
    """x1^T W x2 per output channel (operators/bilinear_tensor_product_op.h):
    x1 [B,M], x2 [B,N], weight [O,M,N] -> [B,O]."""
    def f(a, b, w, *rest):
        out = jnp.einsum("bm,omn,bn->bo", a, w, b)
        return out + rest[0] if rest else out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply(f, *args)


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking (operators/bpr_loss_op.h): for each
    row, -mean_{j != y} log(sigmoid(x_y - x_j))."""
    def f(x, y):
        B, C = x.shape
        y = y.reshape(-1)  # accept [B] or the paddle-standard [B,1]
        pos = jnp.take_along_axis(x, y[:, None], 1)
        diff = pos - x
        logsig = jax.nn.log_sigmoid(diff)
        mask = jnp.ones_like(x).at[jnp.arange(B), y].set(0)
        return -(logsig * mask).sum(1, keepdims=True) / (C - 1)
    return apply(f, input, label)


def center_loss(input, label, centers, alpha=0.1, update=True, name=None):
    """0.5*||x - c_y||^2 with EMA center updates
    (operators/center_loss_op.h): returns (loss [B,1], new_centers).
    `centers [K,D]` is caller-held state (functional re-design of the
    reference's in-place CenterUpdate)."""
    def f(x, y, c):
        cy = c[y]
        diff = x - cy
        loss = 0.5 * (diff ** 2).sum(1, keepdims=True)
        if not update:
            return loss, c
        cnt = jnp.zeros((c.shape[0],), x.dtype).at[y].add(1.0)
        upd = jnp.zeros_like(c).at[y].add(diff)
        new_c = c + alpha * upd / (cnt[:, None] + 1.0)
        return loss, new_c
    return apply(f, input, label, centers, _multi_out=True)


def conv_shift(x, y, name=None):
    """Circular correlation (operators/conv_shift_op.cc): x [B,N],
    y [B,M] (M odd, M<=N) -> out[b,i] = sum_j x[b,(i+j-M//2) mod N]*y[b,j]."""
    def f(a, b):
        N, M = a.shape[1], b.shape[1]
        i = jnp.arange(N)[:, None]
        j = jnp.arange(M)[None, :]
        src = (i + j - M // 2) % N
        return jnp.einsum("bnm,bm->bn", a[:, src], b)
    return apply(f, x, y)


def ctc_align(ids, input_length, blank=0, merge_repeated=True, name=None):
    """CTC greedy-path collapse (operators/ctc_align_op.h): merge repeats
    then drop blanks; output packed left, zero-padded, plus new lens."""
    def f(v, ln):
        B, T = v.shape
        ln = ln.reshape(-1)  # accept [B] or the paddle-standard [B,1]
        t = jnp.arange(T)[None, :]
        valid = t < ln[:, None]
        if merge_repeated:
            first = jnp.concatenate(
                [jnp.ones((B, 1), bool), v[:, 1:] != v[:, :-1]], 1)
        else:
            first = jnp.ones((B, T), bool)
        keep = valid & first & (v != blank)
        order = jnp.argsort(jnp.where(keep, t, T + t), axis=1)
        packed = jnp.take_along_axis(v, order, axis=1)
        new_len = keep.sum(1)
        packed = jnp.where(t < new_len[:, None], packed, 0)
        return packed, new_len
    return apply(f, ids, input_length, _multi_out=True)


def hinge_loss(logits, labels, name=None):
    """max(0, 1 - (2y-1)*x) (operators/hinge_loss_op.h), labels in {0,1}."""
    return apply(lambda x, y: jnp.maximum(
        0.0, 1.0 - (2.0 * y - 1.0) * x), logits, labels)


def log_loss(input, label, epsilon=1e-4, name=None):
    """-(y log(p+eps) + (1-y) log(1-p+eps)) (operators/log_loss_op.h)."""
    return apply(lambda p, y: -y * jnp.log(p + epsilon)
                 - (1.0 - y) * jnp.log(1.0 - p + epsilon), input, label)


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (operators/rank_loss_op.h):
    log(1+exp(o)) - y*o with o = left - right."""
    return apply(lambda y, a, b: jnp.logaddexp(0.0, a - b) - y * (a - b),
                 label, left, right)


def row_conv(x, weight, name=None):
    """Lookahead convolution (operators/row_conv_op.h): x [B,T,D],
    weight [k+1,D] -> out[t] = sum_{j=0..k} x[t+j]*w[j] (zeros past T)."""
    def f(v, w):
        B, T, D = v.shape
        K = w.shape[0]
        t = jnp.arange(T)[None, :, None]
        j = jnp.arange(K)[None, None, :]
        src = t + j
        valid = src < T
        g = v[jnp.arange(B)[:, None, None], jnp.clip(src, 0, T - 1)]
        g = jnp.where(valid[..., None], g, 0)
        return jnp.einsum("btkd,kd->btd", g, w)
    return apply(f, x, weight)


def spp(x, pyramid_height=3, pool_type="max", name=None):
    """Spatial pyramid pooling (operators/spp_op.h): concat adaptive
    2^l x 2^l poolings, flattened -> [B, C*sum(4^l)]."""
    def f(v):
        outs = []
        for lvl in range(pyramid_height):
            bins = 2 ** lvl
            p = _adaptive_pool2d_impl(v, bins, pool_type)
            outs.append(p.reshape(v.shape[0], -1))
        return jnp.concatenate(outs, axis=1)
    return apply(f, x)


def _adaptive_pool2d_impl(v, bins, pool_type):
    # floor-start / ceil-end bins — the same convention as
    # adaptive_avg_pool2d above and the reference spp_op.h
    # (kernel = ceil(dim/bins)), so non-divisible sizes agree
    B, C, H, W = v.shape
    rows = []
    for i in range(bins):
        h0, h1 = (i * H) // bins, -(-((i + 1) * H) // bins)
        cols = []
        for j in range(bins):
            w0, w1 = (j * W) // bins, -(-((j + 1) * W) // bins)
            cell = v[:, :, h0:h1, w0:w1]
            red = cell.max((2, 3)) if pool_type == "max" else cell.mean((2, 3))
            cols.append(red)
        rows.append(jnp.stack(cols, -1))
    return jnp.stack(rows, -2)  # [B,C,bins,bins]


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, name=None):
    """Inverse of max_pool2d-with-index (operators/unpool_op.h): scatter
    pooled values back to their argmax flat positions."""
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else ((stride, stride)
                                    if isinstance(stride, int)
                                    else tuple(stride))

    def f(v, idx):
        B, C, H, W = v.shape
        if output_size is not None:
            oh, ow = output_size[-2], output_size[-1]
        else:
            oh = (H - 1) * st[0] + ks[0] - 2 * padding
            ow = (W - 1) * st[1] + ks[1] - 2 * padding
        flat = jnp.zeros((B, C, oh * ow), v.dtype)
        out = flat.at[
            jnp.arange(B)[:, None, None],
            jnp.arange(C)[None, :, None],
            idx.reshape(B, C, -1)].set(v.reshape(B, C, -1), mode="drop")
        return out.reshape(B, C, oh, ow)
    return apply(f, x, indices)


# --------------------------------------------------------------------------
# reference paddle.nn.functional surface completion (round-4): re-exports
# of ops that live in their subsystem modules, fluid-era aliases, and the
# remaining small lowerings.  Documented non-goals raise with a pointer
# to COVERAGE.md (no bare NotImplementedError).
# --------------------------------------------------------------------------

def _non_goal(name, why):
    def stub(*args, **kwargs):
        raise NotImplementedError(
            f"{name} is a documented non-goal on TPU ({why}); see "
            "COVERAGE.md for the disposition and the supported "
            "alternative")
    stub.__name__ = name
    return stub


def _lod_absorbed(name):
    return _non_goal(
        name, "LoD tensors are replaced by dense padding + seq_len; use "
        "paddle_tpu.text.sequence")


# -- detection / vision (implementations: paddle_tpu.vision.ops) ----------
def __getattr__(name):  # module-level PEP 562 fallback
    _vision_ops = (
        "affine_channel anchor_generator bipartite_match box_clip "
        "box_coder box_decoder_and_assign collect_fpn_proposals "
        "density_prior_box distribute_fpn_proposals generate_proposals "
        "generate_proposal_labels multiclass_nms prior_box prroi_pool "
        "psroi_pool retinanet_detection_output "
        "rpn_target_assign roi_align roi_pool polygon_box_transform "
        "target_assign space_to_depth yolo_box random_crop".split())
    if name in _vision_ops:
        from ...vision import ops as _V

        return getattr(_V, name)
    if name in ("sequence_concat", "sequence_conv", "sequence_enumerate",
                "sequence_expand", "sequence_expand_as", "sequence_pad",
                "sequence_pool", "sequence_reshape", "sequence_reverse",
                "sequence_scatter", "sequence_slice", "sequence_softmax",
                "sequence_unpad", "sequence_mask"):
        from ...text import sequence as _sq

        return getattr(_sq, name)
    if name in ("array_read", "array_write", "array_length",
                "create_array", "tensor_array_to_tensor"):
        from ...static import nn as _snn

        return getattr(_snn, name)
    if name == "linear_chain_crf":
        from ...text import linear_chain_crf as _f

        return _f
    if name == "diag_embed":
        from ...creation import diag as _f

        return _f
    if name == "erf":
        from ... import tensor_ops as _T

        return _T.erf
    if name == "shuffle_channel":
        from ...vision.ops import channel_shuffle as _f

        return _f
    if name == "retinanet_target_assign":
        from ...vision.ops import rpn_target_assign as _f

        return _f
    if name == "random_crop":
        from ...vision.ops import random_crop as _f

        return _f
    raise AttributeError(name)


def deformable_conv(x, offset, mask, num_filters=None, filter_size=None,
                    weight=None, stride=1, padding=0, dilation=1,
                    groups=1, deformable_groups=1, im2col_step=1,
                    bias=None, name=None):
    """fluid.layers.deformable_conv signature over vision.ops.deform_conv2d
    (v1 when mask is None, v2 otherwise)."""
    from ...vision.ops import deform_conv2d

    return deform_conv2d(x, offset, weight, mask=mask, stride=stride,
                         padding=padding, dilation=dilation,
                         groups=groups,
                         deformable_groups=deformable_groups, bias=bias)


def deformable_roi_pooling(input, rois, trans=None, no_trans=True,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=7, pooled_width=7, part_size=None,
                           sample_per_part=4, trans_std=0.1, name=None):
    """Position-sensitive RoI pooling; the learned-offset (trans) variant
    is not implemented — with no_trans it IS psroi_pool (COVERAGE.md)."""
    if not no_trans and trans is not None:
        raise NotImplementedError(
            "deformable_roi_pooling with learned offsets is not "
            "implemented; the no_trans form is vision.ops.psroi_pool "
            "(COVERAGE.md)")
    from ...vision.ops import psroi_pool

    return psroi_pool(input, rois, output_size=(pooled_height,
                                                pooled_width),
                      spatial_scale=spatial_scale)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    from ...vision.ops import yolo_loss

    return yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                     ignore_thresh, downsample_ratio, gt_score,
                     use_label_smooth)


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """SSD post-processing (detection_output_op.cc): decode loc deltas
    against priors, then per-class NMS via multiclass_nms."""
    import numpy as _np

    from ...vision.ops import box_coder, multiclass_nms

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size", box_normalized=True)
    sv = _np.asarray(unwrap(scores))
    dv = _np.asarray(unwrap(decoded))
    if sv.ndim == 2:   # fluid layout [num_priors, C] -> class-major [C, N]
        return multiclass_nms(dv, sv.T,
                              score_threshold=score_threshold,
                              nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                              nms_threshold=nms_threshold,
                              background_label=background_label)
    # batched [N, Np, C]: per-image results as a list (the reference
    # returns a LoD batch; a python list is the dense analog)
    return [multiclass_nms(dv[i] if dv.ndim == 3 else dv, sv[i].T,
                           score_threshold=score_threshold,
                           nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                           nms_threshold=nms_threshold,
                           background_label=background_label)
            for i in range(sv.shape[0])]


# -- sequence step helpers -------------------------------------------------
def sequence_first_step(x, seq_len=None):
    from ...text.sequence import sequence_pool

    if seq_len is None:
        import jax.numpy as _jnp

        seq_len = Tensor(_jnp.full((unwrap(x).shape[0],),
                                   unwrap(x).shape[1], _jnp.int32))
    return sequence_pool(x, seq_len, "FIRST")


def sequence_last_step(x, seq_len=None):
    from ...text.sequence import sequence_pool

    if seq_len is None:
        import jax.numpy as _jnp

        seq_len = Tensor(_jnp.full((unwrap(x).shape[0],),
                                   unwrap(x).shape[1], _jnp.int32))
    return sequence_pool(x, seq_len, "LAST")


# -- pooling / resize aliases ---------------------------------------------
def _spatial_shape(v, data_format):
    return (list(v.shape[1:-1]) if data_format.endswith("C")
            else list(v.shape[2:]))


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCHW", name=None):
    """fluid.layers.pool2d facade over max/avg_pool2d."""
    if global_pooling:
        pool_size = _spatial_shape(unwrap(input), data_format)
        pool_padding = 0
    if pool_type == "max":
        return max_pool2d(input, pool_size, pool_stride, pool_padding,
                          ceil_mode=ceil_mode, data_format=data_format)
    return avg_pool2d(input, pool_size, pool_stride, pool_padding,
                      ceil_mode=ceil_mode, exclusive=exclusive,
                      data_format=data_format)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCDHW", name=None):
    if global_pooling:
        pool_size = _spatial_shape(unwrap(input), data_format)
        pool_padding = 0
    if pool_type == "max":
        return max_pool3d(input, pool_size, pool_stride, pool_padding,
                          ceil_mode=ceil_mode, data_format=data_format)
    return avg_pool3d(input, pool_size, pool_stride, pool_padding,
                      ceil_mode=ceil_mode, exclusive=exclusive,
                      data_format=data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    """Adaptive 3D average pool (floor-start/ceil-end bins like the 2D
    form); one reduce_window when the size divides evenly."""
    os_ = (output_size,) * 3 if isinstance(output_size, int) \
        else tuple(output_size)

    def f(v):
        B, C, D, H, W = v.shape
        if D % os_[0] == 0 and H % os_[1] == 0 and W % os_[2] == 0:
            k = (1, 1, D // os_[0], H // os_[1], W // os_[2])
            s = jax.lax.reduce_window(v, np.dtype(v.dtype).type(0),
                                      jax.lax.add, k, k, "VALID")
            return s / (k[2] * k[3] * k[4])
        out = jnp.zeros((B, C) + os_, v.dtype)
        for i in range(os_[0]):
            d0, d1 = (i * D) // os_[0], -(-((i + 1) * D) // os_[0])
            for j in range(os_[1]):
                h0, h1 = (j * H) // os_[1], -(-((j + 1) * H) // os_[1])
                for k in range(os_[2]):
                    w0, w1 = (k * W) // os_[2], -(-((k + 1) * W) // os_[2])
                    out = out.at[:, :, i, j, k].set(
                        v[:, :, d0:d1, h0:h1, w0:w1].mean((2, 3, 4)))
        return out

    return apply(f, x)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    def f(v):
        B, C, L = v.shape
        outs = []
        for i in range(output_size):
            l0, l1 = (i * L) // output_size, -(-((i + 1) * L) // output_size)
            outs.append(v[:, :, l0:l1].max(-1))
        return jnp.stack(outs, -1)

    return apply(f, x)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    os_ = (output_size,) * 3 if isinstance(output_size, int) \
        else tuple(output_size)

    def f(v):
        B, C, D, H, W = v.shape
        if D % os_[0] == 0 and H % os_[1] == 0 and W % os_[2] == 0:
            k = (1, 1, D // os_[0], H // os_[1], W // os_[2])
            return jax.lax.reduce_window(
                v, np.dtype(v.dtype).type(-np.inf), jax.lax.max, k, k,
                "VALID")
        out = jnp.zeros((B, C) + os_, v.dtype)
        for i in range(os_[0]):
            d0, d1 = (i * D) // os_[0], -(-((i + 1) * D) // os_[0])
            for j in range(os_[1]):
                h0, h1 = (j * H) // os_[1], -(-((j + 1) * H) // os_[1])
                for k in range(os_[2]):
                    w0, w1 = (k * W) // os_[2], -(-((k + 1) * W) // os_[2])
                    out = out.at[:, :, i, j, k].set(
                        v[:, :, d0:d1, h0:h1, w0:w1].max((2, 3, 4)))
        return out

    return apply(f, x)


def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 align_corners=True, align_mode=1, data_format="NCHW",
                 name=None):
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "BICUBIC": "bicubic"}[resample]
    return interpolate(input, size=out_shape, scale_factor=scale,
                       mode=mode, align_corners=align_corners)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    v = unwrap(input)
    H, W = v.shape[2], v.shape[3]
    short = min(H, W)
    ratio = out_short_len / short
    return image_resize(input,
                        [int(round(H * ratio)), int(round(W * ratio))],
                        resample=resample)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, "BILINEAR", align_corners)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   align_corners=True):
    return image_resize(input, out_shape, scale, "NEAREST", align_corners)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, "TRILINEAR", align_corners)


# -- misc fluid layers -----------------------------------------------------
def fc(input, size, num_flatten_dims=1, weight=None, bias=None, name=None):
    """fluid.layers.fc: flatten trailing dims then linear; weight/bias
    must be provided (create_parameter) — the layer form is nn.Linear."""
    v = unwrap(input)
    lead = v.shape[:num_flatten_dims]
    from ... import tensor_ops as T

    flat = T.reshape(input, list(lead) + [-1])
    if weight is None:
        raise ValueError("functional fc needs an explicit weight "
                         "(paddle.create_parameter); use nn.Linear for "
                         "the parameterized layer form")
    return linear(flat, weight, bias)


def bilinear_tensor_product(x, y, weight, bias=None, name=None):
    return bilinear(x, y, weight, bias)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    """fluid smooth_l1 (sum form, optional elementwise weights):
    inside_weight scales the diff BEFORE the Huber switch, outside_weight
    scales the loss after it (smooth_l1_loss_op.cc)."""
    has_iw = inside_weight is not None
    has_ow = outside_weight is not None

    def f(a, b, *w):
        iw = w[0] if has_iw else jnp.ones_like(a)
        ow = w[-1] if has_ow else jnp.ones_like(a)
        d = (a - b) * iw
        s2 = (sigma or 1.0) ** 2
        loss = jnp.where(jnp.abs(d) < 1.0 / s2,
                         0.5 * s2 * d * d, jnp.abs(d) - 0.5 / s2)
        return (loss * ow).sum(axis=tuple(range(1, a.ndim)),
                               keepdims=False)[..., None]

    args = [x, y] + [a for a in (inside_weight, outside_weight)
                     if a is not None]
    return apply(f, *args)


def soft_relu(x, threshold=40.0, name=None):
    """log(1 + exp(clip(x, -t, t))) (activation_op.h SoftRelu)."""
    return apply(lambda v: jnp.log1p(jnp.exp(jnp.clip(v, -threshold,
                                                      threshold))), x)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - 2|X∩Y|/(|X|+|Y|) over the trailing class axis (dice_loss in
    fluid/layers/nn.py)."""
    def f(p, y):
        yf = jax.nn.one_hot(y.squeeze(-1), p.shape[-1], dtype=p.dtype) \
            if y.shape[-1] == 1 and y.dtype in (jnp.int32, jnp.int64) \
            else y.astype(p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = (p * yf).sum(reduce_dims)
        union = p.sum(reduce_dims) + yf.sum(reduce_dims)
        return (1.0 - (2.0 * inter + epsilon) / (union + epsilon)).mean()

    return apply(f, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (fluid/layers/loss.py npair_loss)."""
    def f(a, p, y):
        B = a.shape[0]
        logits = a @ p.T
        tgt = (y[:, None] == y[None, :]).astype(logits.dtype)
        tgt = tgt / tgt.sum(-1, keepdims=True)
        logp = jax.nn.log_softmax(logits, -1)
        xe = -(tgt * logp).sum(-1).mean()
        reg = (a * a).sum() / B + (p * p).sum() / B
        return xe + l2_reg * reg * 0.25

    return apply(f, anchor, positive, labels)


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix (fsp_op.cc): [B, Cx, Cy] =
    x·y over the spatial map, normalized by H*W."""
    return apply(lambda a, b: jnp.einsum("bchw,bdhw->bcd", a, b)
                 / (a.shape[2] * a.shape[3]), x, y)


def warpctc(input, label, input_length=None, label_length=None,
            blank=0, norm_by_times=False):
    return ctc_loss(input, label, input_length, label_length, blank=blank)


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Greedy CTC decode (ctc_align over the argmax path)."""
    from ... import tensor_ops as T

    ids = T.argmax(input, axis=-1)
    if input_length is None:
        import jax.numpy as _jnp

        v = unwrap(ids)
        input_length = Tensor(_jnp.full((v.shape[0],), v.shape[1],
                                        _jnp.int32))
    return ctc_align(ids, input_length, blank=blank)


def crf_decoding(input, transition, seq_len=None, label=None, name=None):
    """Viterbi decode with the CRF's [K+2, K] transition layout
    (crf_decoding_op.cc): returns the best path ids."""
    import jax.numpy as _jnp

    from ...text import ViterbiDecoder

    tr = unwrap(transition)
    dec = ViterbiDecoder(Tensor(tr[2:]), include_bos_eos_tag=False)
    v = unwrap(input)
    if seq_len is None:
        seq_len = Tensor(_jnp.full((v.shape[0],), v.shape[1], _jnp.int32))
    _, paths = dec(input, seq_len)
    return paths


def data_norm(input, batch_size=None, batch_sum=None,
              batch_square_sum=None, epsilon=1e-4, **kwargs):
    """data_norm_op.cc: normalize by ACCUMULATED statistics when the
    size/sum/square-sum accumulators are given (mean = sum/size,
    scale = rsqrt(square_sum/size - mean^2 + eps) — the op's serving
    path); falls back to the batch's own moments without them."""
    if batch_size is not None and batch_sum is not None \
            and batch_square_sum is not None:
        def f(v, n, s, sq):
            mean = s / n
            scale = jax.lax.rsqrt(sq / n - mean * mean + epsilon)
            return (v - mean) * scale

        return apply(f, input, batch_size, batch_sum, batch_square_sum)

    def f(v):
        mu = v.mean(0, keepdims=True)
        var = v.var(0, keepdims=True)
        return (v - mu) * jax.lax.rsqrt(var + epsilon)

    return apply(f, input)


_step_counters = {}


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Per-name step counter (fluid layers.autoincreased_step_counter):
    python-int state keyed by counter_name, returned as an int64 Tensor
    (the reference op's dtype)."""
    import jax.numpy as _jnp

    key = counter_name or "@STEP_COUNTER@"
    if key not in _step_counters:
        _step_counters[key] = begin
    else:
        _step_counters[key] += step
    dt = _jnp.int64 if jax.config.jax_enable_x64 else _jnp.int32
    return Tensor(_jnp.asarray(_step_counters[key], dt))


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Functional spectral normalization (spectral_norm_op.cc): a few
    power iterations estimate sigma_max; returns weight / sigma."""
    def f(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), w.dtype)
        v = None
        for _ in range(builtins_max(power_iters, 1)):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma

    import builtins as _b
    builtins_max = _b.max
    return apply(f, weight)


# -- documented non-goals / LoD-era stubs ---------------------------------
nce = _non_goal("nce", "host-side negative-sampling table")
hsigmoid_loss = _non_goal("hsigmoid_loss", "host-side Huffman tree")
hash = _non_goal("hash", "PS-era recommender op")  # noqa: A001
filter_by_instag = _non_goal("filter_by_instag", "PS-era recommender op")
continuous_value_model = _non_goal("continuous_value_model",
                                   "PS-era recommender op")
teacher_student_sigmoid_loss = _non_goal("teacher_student_sigmoid_loss",
                                         "PS-era recommender op")
similarity_focus = _non_goal("similarity_focus", "PS-era recommender op")
multi_box_head = _non_goal(
    "multi_box_head", "SSD graph-builder helper; compose prior_box + "
    "conv heads directly")
roi_perspective_transform = _non_goal(
    "roi_perspective_transform", "OCR-specific; compose grid_sample + "
    "roi_align")
generate_mask_labels = _non_goal("generate_mask_labels",
                                 "Mask-RCNN host-side label carving")
im2sequence = _lod_absorbed("im2sequence")
lod_append = _lod_absorbed("lod_append")
lod_reset = _lod_absorbed("lod_reset")
reorder_lod_tensor_by_rank = _lod_absorbed("reorder_lod_tensor_by_rank")
dynamic_gru = _lod_absorbed("dynamic_gru")
dynamic_lstm = _lod_absorbed("dynamic_lstm")
dynamic_lstmp = _lod_absorbed("dynamic_lstmp")
merge_selected_rows = _non_goal("merge_selected_rows",
                                "SelectedRows do not exist (dense grads)")


def gru_unit(input, hidden, weight=None, bias=None, **kwargs):
    raise NotImplementedError(
        "gru_unit's fused fluid contract is absorbed by nn.GRUCell "
        "(COVERAGE.md: lax.scan is the recurrence primitive)")


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, **kwargs):
    raise NotImplementedError(
        "lstm_unit's fused fluid contract is absorbed by nn.LSTMCell "
        "(COVERAGE.md: lax.scan is the recurrence primitive)")


def lstm(input, init_h, init_c, max_len=None, hidden_size=None,
         num_layers=1, **kwargs):
    raise NotImplementedError(
        "fluid.layers.lstm (cudnn contract) is absorbed by nn.LSTM "
        "(COVERAGE.md)")


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run an RNN cell over time (paddle.nn.functional.rnn > fluid
    rnn): host-level loop over the cell, batch-major by default."""
    from ... import tensor_ops as T

    x = inputs
    if time_major:
        x = T.transpose(x, [1, 0, 2])
    B = unwrap(x).shape[0]
    Tlen = unwrap(x).shape[1]
    state = cell.get_initial_states(B) if initial_states is None \
        else initial_states
    outs = []
    ts = range(Tlen - 1, -1, -1) if is_reverse else range(Tlen)
    for t in ts:
        out, state = cell(x[:, t], state)
        outs.append(out)
    if is_reverse:
        outs = outs[::-1]
    y = T.stack(outs, axis=1)
    if time_major:
        y = T.transpose(y, [1, 0, 2])
    return y, state


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    """Bidirectional rnn(): concat of forward and reversed-backward
    passes."""
    from ... import tensor_ops as T

    fw, st_f = rnn(cell_fw, inputs, time_major=time_major)
    bw, st_b = rnn(cell_bw, inputs, time_major=time_major,
                   is_reverse=True)
    return T.concat([fw, bw], axis=-1), (st_f, st_b)


def pad2d(input, paddings=0, mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    """fluid pad2d over F.pad ([top, bottom, left, right] order)."""
    p = [paddings] * 4 if isinstance(paddings, int) else list(paddings)
    # fluid order t,b,l,r -> pad() 2d order l,r,t,b
    return pad(input, [p[2], p[3], p[0], p[1]], mode=mode,
               value=pad_value, data_format=data_format)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y up to x's shape with trailing constant padding
    (pad_constant_like_op.cc)."""
    def f(a, b):
        pads = [(0, int(sa) - int(sb)) for sa, sb in zip(a.shape, b.shape)]
        return jnp.pad(b, pads, constant_values=pad_value)

    return apply(f, x, y)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    """fluid brelu (activation_op.cc BRelu): clip to [t_min, t_max]."""
    return hardtanh(x, min=t_min, max=t_max)


def gather_tree(ids, parents):
    """Beam-search backtrace (paddle.nn.functional.gather_tree alias of
    the text decoding op — reference nn/functional/__init__.py exports
    it here too)."""
    from ...text import gather_tree as _gt

    return _gt(ids, parents)


# reference-structure submodule aliases (python/paddle/nn/functional/
# {activation,common,conv,extension,loss,pooling}.py): imported LAST so
# they can re-export the flat surface above
from . import activation  # noqa: E402,F401
from . import common  # noqa: E402,F401
from . import conv  # noqa: E402,F401
from . import extension  # noqa: E402,F401
from . import loss  # noqa: E402,F401
from . import pooling  # noqa: E402,F401
