"""nn.functional — stateless NN ops.

Reference parity: python/paddle/nn/functional/* over the C++ op zoo
(activation ops, conv2d/cudnn conv, pool2d, batch/layer/group norm, dropout,
softmax_with_cross_entropy_op.cc:301, lookup_table_v2 embedding, ...).

TPU-native: each op is a jnp/lax lowering; convs and matmuls lower to XLA
convolution/dot (MXU); fused paths (flash attention, fused LN/softmax-xent)
swap in Pallas kernels via paddle_tpu.ops when FLAGS_use_pallas_kernels is on.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as _random
from ...framework.flags import flag
from ...tensor import Tensor, apply, unwrap
from ... import tensor_ops as T

pad = T.pad  # re-export (paddle.nn.functional.pad)


# ---------------------------------------------------------------------------
# activations (operators/activation_op.cc family)
# ---------------------------------------------------------------------------
def relu(x, name=None):
    return apply(jax.nn.relu, x)


def relu_(x, name=None):
    out = relu(x)
    x._value = out.value
    return x


def relu6(x, name=None):
    return apply(lambda v: jnp.clip(v, 0.0, 6.0), x)


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, x)


def tanh(x, name=None):
    return apply(jnp.tanh, x)


def gelu(x, approximate=False, name=None):
    return apply(lambda v: jax.nn.gelu(v, approximate=approximate), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda v: jax.nn.leaky_relu(v, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.elu(v, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x)


def celu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.celu(v, alpha), x)


def silu(x, name=None):
    return apply(jax.nn.silu, x)


def swish(x, name=None):
    return apply(jax.nn.silu, x)


def mish(x, name=None):
    return apply(lambda v: v * jnp.tanh(jax.nn.softplus(v)), x)


def hardswish(x, name=None):
    return apply(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply(lambda v: jnp.clip(v, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.sign(v) * jnp.maximum(jnp.abs(v) - threshold, 0.0), x)


def tanhshrink(x, name=None):
    return apply(lambda v: v - jnp.tanh(v), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda v: jnp.where(v * beta > threshold, v,
                                     jax.nn.softplus(v * beta) / beta), x)


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, x)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(lambda v: jnp.where(v > threshold, v, 0.0), x)


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(v, w):
        if w.size == 1:
            ww = w.reshape(())
        else:
            shape = [1] * v.ndim
            c_axis = 1 if data_format[1] == "C" else v.ndim - 1
            shape[c_axis] = w.size
            ww = w.reshape(shape)
        return jnp.where(v > 0, v, ww * v)
    return apply(f, x, weight)


def softmax(x, axis=-1, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            from ...framework.dtype import convert_dtype
            v = v.astype(convert_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)
    return apply(f, x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply(lambda v: jax.nn.log_softmax(v, axis=axis), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = _random.split_key()

    def f(v):
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, v.shape, v.dtype, 1e-20, 1.0)))
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis) \
                if hasattr(jnp, "put_along_axis") else \
                jax.nn.one_hot(jnp.squeeze(idx, axis), y.shape[axis], axis=axis,
                               dtype=y.dtype)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return apply(f, x)


def glu(x, axis=-1, name=None):
    return apply(lambda v: jax.nn.glu(v, axis=axis), x)


def maxout(x, groups, axis=1, name=None):
    def f(v):
        c = v.shape[axis]
        new_shape = list(v.shape)
        new_shape[axis] = c // groups
        new_shape.insert(axis + 1, groups)
        return jnp.max(v.reshape(new_shape), axis=axis + 1)
    return apply(f, x)


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------
def linear(x, weight, bias=None, name=None):
    """paddle convention: weight shape [in_features, out_features]."""
    from ...amp import white_cast

    if bias is None:
        return apply(lambda v, w: jnp.matmul(*white_cast(v, w)), x, weight)

    def f(v, w, b):
        v, w = white_cast(v, w)
        return v @ w + b.astype(v.dtype)

    return apply(f, x, weight, bias)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply(f, x, weight)


def one_hot(x, num_classes, name=None):
    return apply(lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32), x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k
    args = (label,) if prior_dist is None else (label, prior_dist)
    return apply(f, *args)


# ---------------------------------------------------------------------------
# convolution (conv2d + cudnn variants → XLA conv_general_dilated)
# ---------------------------------------------------------------------------
def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(u) for u in v)


def _conv_padding(padding, nsp, strides=None):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * nsp
    padding = list(padding)
    if all(isinstance(p, (list, tuple)) for p in padding):
        # possibly includes batch/channel dims (paddle allows 4-elem pair list)
        pairs = [tuple(p) for p in padding]
        if len(pairs) == nsp + 2:
            pairs = pairs[2:]
        return pairs
    if len(padding) == nsp:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * nsp:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nsp)]
    raise ValueError(f"bad padding {padding}")


def _dimension_numbers(nsp, channel_last):
    sp = "DHW"[-nsp:]
    if channel_last:
        return (f"N{sp}C", f"{sp}IO"[::1].replace(sp, sp) if False else f"O{sp}I"[0:0] or f"{sp}",)  # unreachable
    return None


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, nsp,
          transpose=False, output_padding=0):
    channel_last = data_format[-1] == "C"
    stride = _norm_tuple(stride, nsp)
    dilation = _norm_tuple(dilation, nsp)
    pad_spec = _conv_padding(padding, nsp)
    sp = "DHW"[3 - nsp:]
    if channel_last:
        lhs_spec = "N" + sp + "C"
        out_spec = "N" + sp + "C"
    else:
        lhs_spec = "NC" + sp
        out_spec = "NC" + sp
    rhs_spec = "OI" + sp  # paddle weight layout: [out_c, in_c/groups, *k]

    def f(v, w, *b):
        from ...amp import white_cast

        v, w = white_cast(v, w)
        if b:
            b = (b[0].astype(v.dtype),)
        if transpose:
            # paddle conv_transpose weight: [in_c, out_c/groups, *k].
            # Express as a fractionally-strided conv: dilate the input by
            # `stride`, swap the kernel's I/O dims and flip it spatially
            # (the gradient-of-conv identity).
            k = w.shape[2:]
            if isinstance(pad_spec, str):
                pads = pad_spec
            else:
                # output = (in-1)*s - 2p + k (+ output_padding)
                pads = [(d * (kk - 1) - p[0], d * (kk - 1) - p[1] + op)
                        for kk, p, d, op in zip(
                            k, pad_spec, dilation,
                            _norm_tuple(output_padding, nsp))]
            wt = jnp.swapaxes(w, 0, 1) if groups == 1 else _group_swap(w, groups)
            wt = jnp.flip(wt, axis=tuple(range(2, wt.ndim)))
            out = jax.lax.conv_general_dilated(
                v, wt,
                window_strides=(1,) * nsp,
                padding=pads,
                lhs_dilation=stride,
                rhs_dilation=dilation,
                dimension_numbers=(lhs_spec, rhs_spec, out_spec),
                feature_group_count=groups,
            )
        else:
            out = jax.lax.conv_general_dilated(
                v, w,
                window_strides=stride,
                padding=pad_spec,
                rhs_dilation=dilation,
                dimension_numbers=(lhs_spec, rhs_spec, out_spec),
                feature_group_count=groups,
            )
        if b:
            bshape = [1] * out.ndim
            bshape[out_spec.index("C")] = b[0].size
            out = out + b[0].reshape(bshape)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(f, *args)


def _group_swap(w, groups):
    # [in_c, out_c/groups, *k] -> grouped OIHW-transposed layout
    ic, ocg = w.shape[0], w.shape[1]
    k = w.shape[2:]
    w = w.reshape((groups, ic // groups, ocg) + k)
    w = jnp.swapaxes(w, 1, 2)
    return w.reshape((groups * ocg, ic // groups) + k)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, fmt, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCL",
                     name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, fmt, 1,
                 transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format,
                 2, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format,
                 3, transpose=True, output_padding=output_padding)


# ---------------------------------------------------------------------------
# pooling (pool2d op → lax.reduce_window)
# ---------------------------------------------------------------------------
def _pool(x, kernel, stride, padding, nsp, data_format, op, ceil_mode=False,
          include_pad=False, count_include_pad=True):
    channel_last = data_format[-1] == "C"
    kernel = _norm_tuple(kernel, nsp)
    stride = _norm_tuple(stride if stride is not None else kernel, nsp)
    pad_spec = _conv_padding(padding, nsp)

    def f(v):
        if channel_last:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = [(0, 0)] + (pad_spec if isinstance(pad_spec, list)
                               else [(0, 0)] * nsp) + [(0, 0)] \
                if not isinstance(pad_spec, str) else pad_spec
        else:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = [(0, 0), (0, 0)] + (pad_spec if isinstance(pad_spec, list)
                                       else [(0, 0)] * nsp) \
                if not isinstance(pad_spec, str) else pad_spec
        if isinstance(pads, str):
            pads_resolved = jax.lax.padtype_to_pads(v.shape, window, strides,
                                                    pads)
        else:
            pads_resolved = pads
        if ceil_mode and not isinstance(pads_resolved, str):
            # extend right pads so ceil-divided windows fit
            pads_resolved = list(pads_resolved)
            sp_offset = 1 if channel_last else 2
            for i in range(nsp):
                d = sp_offset + i
                size = v.shape[d] + pads_resolved[d][0] + pads_resolved[d][1]
                rem = (size - kernel[i]) % stride[i]
                if rem:
                    pads_resolved[d] = (pads_resolved[d][0],
                                        pads_resolved[d][1] + stride[i] - rem)
        if op == "max":
            # init must carry the operand dtype as a CONCRETE numpy scalar:
            # a python -inf becomes f64 under x64 (CPU) and poisons the
            # graph, while a jax array init breaks reduce_window transpose
            init = (np.dtype(v.dtype).type(-np.inf)
                    if jnp.issubdtype(v.dtype, jnp.floating)
                    else np.dtype(v.dtype).type(jnp.iinfo(v.dtype).min))
            return jax.lax.reduce_window(v, init, jax.lax.max, window, strides,
                                         pads_resolved)
        # avg
        ones = jnp.ones_like(v)
        s = jax.lax.reduce_window(v, np.dtype(v.dtype).type(0), jax.lax.add,
                                  window, strides, pads_resolved)
        if count_include_pad:
            denom = float(np.prod(kernel))
            return s / denom
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                    pads_resolved)
        return s / cnt

    return apply(f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool(x, kernel_size, stride, padding, 1, fmt, "max", ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "max", ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool(x, kernel_size, stride, padding, 1, fmt, "avg", ceil_mode,
                 count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg",
                 ceil_mode, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg",
                 ceil_mode, count_include_pad=not exclusive)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_hw = _norm_tuple(output_size, 2)

    def f(v):
        channel_last = data_format[-1] == "C"
        h_ax, w_ax = (1, 2) if channel_last else (2, 3)
        H, W = v.shape[h_ax], v.shape[w_ax]
        oh, ow = out_hw
        if H % oh == 0 and W % ow == 0:
            kh, kw = H // oh, W // ow
            window = [1, 1, 1, 1]
            window[h_ax], window[w_ax] = kh, kw
            s = jax.lax.reduce_window(v, 0.0, jax.lax.add, tuple(window),
                                      tuple(window), "VALID")
            return s / (kh * kw)
        # general: mean over computed bins (static shapes)
        hi = [(int(math.floor(i * H / oh)), int(math.ceil((i + 1) * H / oh)))
              for i in range(oh)]
        wi = [(int(math.floor(j * W / ow)), int(math.ceil((j + 1) * W / ow)))
              for j in range(ow)]
        rows = []
        for (h0, h1) in hi:
            cols = []
            for (w0, w1) in wi:
                sl = [slice(None)] * v.ndim
                sl[h_ax], sl[w_ax] = slice(h0, h1), slice(w0, w1)
                cols.append(jnp.mean(v[tuple(sl)], axis=(h_ax, w_ax),
                                     keepdims=True))
            rows.append(jnp.concatenate(cols, axis=w_ax))
        return jnp.concatenate(rows, axis=h_ax)

    return apply(f, x)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_hw = _norm_tuple(output_size, 2)

    def f(v):
        H, W = v.shape[2], v.shape[3]
        oh, ow = out_hw
        kh, kw = H // oh, W // ow
        return jax.lax.reduce_window(v, np.dtype(v.dtype).type(-np.inf),
                                     jax.lax.max,
                                     (1, 1, kh, kw), (1, 1, kh, kw), "VALID")
    return apply(f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    def f(v):
        L = v.shape[-1]
        o = output_size if isinstance(output_size, int) else output_size[0]
        k = L // o
        return jax.lax.reduce_window(v, 0.0, jax.lax.add, (1, 1, k), (1, 1, k),
                                     "VALID") / k
    return apply(f, x)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    ns = (normalized_shape,) if isinstance(normalized_shape, int) \
        else tuple(normalized_shape)
    naxes = len(ns)

    from ...ops import fused as _fused
    if (flag("FLAGS_use_pallas_kernels") and naxes == 1 and weight is not None
            and bias is not None):
        return _fused.layer_norm(x, weight, bias, epsilon)

    def f(v, *wb):
        axes = tuple(range(v.ndim - naxes, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mean), axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [a for a in (x, weight, bias) if a is not None]
    return apply(f, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    channel_last = data_format[-1] == "C" and len(data_format) > 2

    def f(v, rm, rv, *wb):
        c_ax = v.ndim - 1 if channel_last else (1 if v.ndim > 1 else 0)
        axes = tuple(i for i in range(v.ndim) if i != c_ax)
        use_batch = training and not use_global_stats
        if use_batch:
            # E[x^2] - E[x]^2 instead of jnp.var's two dependent passes:
            # both reductions read x once, so XLA multi-output-fuses them
            # into a single sweep over the (usually conv-output) operand —
            # BN train is HBM-bound and this drops one full pass
            mean = jnp.mean(v, axis=axes)
            mean_sq = jnp.mean(jnp.square(v), axis=axes)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        else:
            mean, var = rm, rv
        shape = [1] * v.ndim
        shape[c_ax] = v.shape[c_ax]
        out = (v - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [a for a in (x, running_mean, running_var, weight, bias)
            if a is not None]
    out = apply(f, *args)

    # running-stat update (mirrors batch_norm_op: stats updated in forward)
    if training and not use_global_stats:
        v = unwrap(x)
        c_ax = v.ndim - 1 if channel_last else (1 if v.ndim > 1 else 0)
        axes = tuple(i for i in range(v.ndim) if i != c_ax)
        with jax.ensure_compile_time_eval() if False else _noop_ctx():
            bm = jnp.mean(v, axis=axes)
            n = np.prod([v.shape[a] for a in axes])
            # same sum/sum-sq formulation as the normalize path so the
            # whole stats computation CSEs with it inside one jit
            bv = jnp.maximum(jnp.mean(jnp.square(v), axis=axes)
                             - jnp.square(bm), 0.0) * (n / max(n - 1, 1))
            running_mean.set_value(running_mean.value * momentum + bm * (1 - momentum))
            running_var.set_value(running_var.value * momentum + bv * (1 - momentum))
    return out


import contextlib as _ctxlib


def _noop_ctx():
    return _ctxlib.nullcontext()


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def f(v, *wb):
        axes = tuple(range(2, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [a for a in (x, weight, bias) if a is not None]
    return apply(f, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format[-1] == "C" and len(data_format) > 2

    def f(v, *wb):
        if channel_last:
            v_ = jnp.moveaxis(v, -1, 1)
        else:
            v_ = v
        N, C = v_.shape[0], v_.shape[1]
        g = v_.reshape((N, num_groups, C // num_groups) + v_.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v_.shape)
        shape = [1, C] + [1] * (v_.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = [a for a in (x, weight, bias) if a is not None]
    return apply(f, *args)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply(lambda v: v / jnp.maximum(
        jnp.linalg.norm(v, ord=p, axis=axis, keepdims=True), epsilon), x)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(v):
        sq = jnp.square(v)
        half = size // 2
        c_ax = 1
        pad_width = [(0, 0)] * v.ndim
        pad_width[c_ax] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_width)
        window = [1] * v.ndim
        window[c_ax] = size
        s = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(window),
                                  (1,) * v.ndim, "VALID")
        return v / jnp.power(k + alpha * s, beta)
    return apply(f, x)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = _random.split_key()

    def f(v):
        shape = list(v.shape)
        if axis is not None:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0)
        return jnp.where(keep, v, 0.0)

    return apply(f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = _random.split_key()

    def f(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / math.sqrt((1 - p) * (1 + p * alpha_p ** 2))) if p < 1 else 0.0
        b = -a * alpha_p * p
        return a * jnp.where(keep, v, alpha_p) + b
    return apply(f, x)


# ---------------------------------------------------------------------------
# losses (softmax_with_cross_entropy_op.cc:301 etc.)
# ---------------------------------------------------------------------------
def _reduce_loss(loss_fn_out, reduction):
    if reduction == "mean":
        return T.mean(loss_fn_out)
    if reduction == "sum":
        return T.sum(loss_fn_out)
    return loss_fn_out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    from ...ops import fused as _fused
    if (flag("FLAGS_use_pallas_kernels") and use_softmax and not soft_label
            and weight is None and axis in (-1, None)):
        raw = _fused.softmax_cross_entropy(input, label, ignore_index)
        return _reduce_loss(raw, reduction) if reduction != "none" else raw

    def f(logits, lbl, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-15, 1.0))
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis)
        else:
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logp.ndim:
                lbl_i = jnp.squeeze(lbl_i, axis)
            picked = jnp.take_along_axis(
                logp, lbl_i[..., None] if axis in (-1, logp.ndim - 1)
                else jnp.expand_dims(lbl_i, axis), axis=axis)
            loss = -jnp.squeeze(picked, axis)
            valid = lbl_i != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if w:
                cw = jnp.take(w[0], jnp.clip(lbl_i, 0, None), axis=0)
                loss = loss * jnp.where(valid, cw, 0.0)
        return loss

    args = [input, label] + ([weight] if weight is not None else [])
    raw = apply(f, *args)
    if reduction == "none":
        return raw
    if reduction == "sum":
        return T.sum(raw)
    if soft_label or (ignore_index == -100 and weight is None):
        return T.mean(raw)

    # mean over valid entries, weighted if a class-weight vector was given
    nd = len(unwrap(input).shape)

    def denom_fn(l, *w):
        li = l.astype(jnp.int32)
        if li.ndim == nd:
            li = jnp.squeeze(li, axis)
        valid = li != ignore_index
        if w:
            cw = jnp.take(w[0], jnp.clip(li, 0, None), axis=0)
            return jnp.sum(jnp.where(valid, cw, 0.0))
        return jnp.sum(valid.astype(jnp.float32))

    denom = apply(denom_fn, label, *([weight] if weight is not None else []))
    return T.sum(raw) / denom


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = T.unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def f(logp, lbl, *w):
        lbl_i = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, lbl_i[..., None], axis=-1)
        loss = -jnp.squeeze(picked, -1)
        if w:
            loss = loss * jnp.take(w[0], lbl_i, axis=0)
        return jnp.where(lbl_i == ignore_index, 0.0, loss)
    args = [input, label] + ([weight] if weight is not None else [])
    return _reduce_loss(apply(f, *args), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _reduce_loss(apply(lambda a, b: jnp.square(a - b), input, label),
                        reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _reduce_loss(apply(lambda a, b: jnp.abs(a - b), input, label),
                        reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        return jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta) * delta \
            / delta
    return _reduce_loss(apply(lambda a, b: jnp.where(
        jnp.abs(a - b) < delta, 0.5 * jnp.square(a - b) / delta,
        jnp.abs(a - b) - 0.5 * delta), input, label), reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, l, *w):
        eps = 1e-12
        loss = -(l * jnp.log(jnp.clip(p, eps, None))
                 + (1 - l) * jnp.log(jnp.clip(1 - p, eps, None)))
        if w:
            loss = loss * w[0]
        return loss
    args = [input, label] + ([weight] if weight is not None else [])
    return _reduce_loss(apply(f, *args), reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, l, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        log_sig = jax.nn.log_sigmoid(z)
        log_one_minus = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * l * log_sig + (1 - l) * log_one_minus)
        else:
            loss = -(l * log_sig + (1 - l) * log_one_minus)
        if w is not None:
            loss = loss * w
        return loss
    args = [logit, label] + [a for a in (weight, pos_weight) if a is not None]
    return _reduce_loss(apply(f, *args), reduction)


def kl_div(input, label, reduction="mean", name=None):
    raw = apply(lambda lp, t: t * (jnp.log(jnp.clip(t, 1e-12, None)) - lp),
                input, label)
    if reduction == "batchmean":
        return T.sum(raw) / unwrap(input).shape[0]
    return _reduce_loss(raw, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return _reduce_loss(apply(
        lambda a, b, l: jnp.maximum(0.0, -l * (a - b) + margin),
        input, other, label), reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _reduce_loss(apply(
        lambda a, l: jnp.where(l == 1, a, jnp.maximum(0.0, margin - a)),
        input, label), reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return apply(f, x1, x2)


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    sim = cosine_similarity(input1, input2, axis=1)
    return _reduce_loss(apply(
        lambda s, l: jnp.where(l == 1, 1 - s, jnp.maximum(0.0, s - margin)),
        sim, label), reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, l, *n):
        p = jax.nn.sigmoid(z)
        ce = -(l * jax.nn.log_sigmoid(z) + (1 - l) * jax.nn.log_sigmoid(-z))
        p_t = p * l + (1 - p) * (1 - l)
        a_t = alpha * l + (1 - alpha) * (1 - l)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return loss
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return _reduce_loss(apply(f, *args), reduction)


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard alpha recursion in log space (warpctc analog)."""
    def f(lp, lab, il, ll):
        # lp: [T, B, C] logits; convert to log-probs
        lp = jax.nn.log_softmax(lp, axis=-1)
        Tmax, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = -1e30
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), ext[:, 0]])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(ll > 0, lp[0, jnp.arange(B), ext[:, 1]], neg_inf))

        same = jnp.concatenate(
            [jnp.full((B, 2), False),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a = alpha
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), a[:, :-1]], 1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), a[:, :-2]], 1)
            a2 = jnp.where(same, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(a, a1), a2)
            new = m + jnp.log(jnp.exp(a - m) + jnp.exp(a1 - m)
                              + jnp.exp(a2 - m) + 1e-30)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return new + emit, None

        def scan_body(carry, t):
            alpha = carry
            new, _ = step(alpha, lp[t])
            alpha = jnp.where((t < il)[:, None], new, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, Tmax))
        idx_last = 2 * ll.astype(jnp.int32)
        b_idx = jnp.arange(B)
        final = jnp.logaddexp(
            alpha[b_idx, idx_last],
            jnp.where(ll > 0, alpha[b_idx, jnp.maximum(idx_last - 1, 0)], neg_inf))
        return -final

    raw = apply(f, log_probs, labels, input_lengths, label_lengths)
    if reduction == "mean":
        return T.mean(apply(lambda r, ll: r / jnp.maximum(ll, 1), raw,
                            label_lengths))
    return _reduce_loss(raw, reduction)


# ---------------------------------------------------------------------------
# attention + sequence utilities
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """[B, S, H, D] layout. Uses the Pallas flash-attention kernel on TPU
    when enabled (ops/pallas/flash_attention.py), else an XLA softmax path."""
    from ...ops import fused as _fused
    return _fused.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
        is_causal=is_causal, training=training)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    from ...framework.dtype import convert_dtype
    ml = maxlen

    def f(l):
        m = ml if ml is not None else int(jnp.max(l))
        ar = jnp.arange(m)
        return (ar[None, :] < l[..., None]).astype(convert_dtype(dtype))
    return apply(f, lengths)


# ---------------------------------------------------------------------------
# vision ops
# ---------------------------------------------------------------------------
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    def f(v):
        channel_last = data_format[-1] == "C"
        sp_axes = list(range(1, v.ndim - 1)) if channel_last \
            else list(range(2, v.ndim))
        in_sizes = [v.shape[a] for a in sp_axes]
        if size is not None:
            out_sizes = [int(unwrap(s)) for s in
                         (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(in_sizes)
            out_sizes = [int(s * f_) for s, f_ in zip(in_sizes, sf)]
        new_shape = list(v.shape)
        for a, s in zip(sp_axes, out_sizes):
            new_shape[a] = s
        m = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        if m == "nearest":
            return jax.image.resize(v, new_shape, method="nearest")
        if align_corners:
            # jax.image.resize has no align_corners; emulate via per-axis map
            out = v
            for a, s_out in zip(sp_axes, out_sizes):
                s_in = out.shape[a]
                if s_out == s_in:
                    continue
                idx = jnp.linspace(0.0, s_in - 1, s_out)
                lo = jnp.floor(idx).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, s_in - 1)
                w = (idx - lo).astype(v.dtype)
                shape = [1] * out.ndim
                shape[a] = s_out
                wv = w.reshape(shape)
                out = jnp.take(out, lo, axis=a) * (1 - wv) + \
                    jnp.take(out, hi, axis=a) * wv
            return out
        return jax.image.resize(v, new_shape, method=m)
    return apply(f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(v):
        N, C, H, W = v.shape
        v = v.reshape(N, C // (r * r), r, r, H, W)
        v = v.transpose(0, 1, 4, 2, 5, 3)
        return v.reshape(N, C // (r * r), H * r, W * r)
    return apply(f, x)


def _unfold_pads(paddings):
    """1/2/4-int padding forms (reference unfold_op): 1 → all sides,
    2 → (ph, pw), 4 → (top, left, bottom, right). Returns ((pt,pb),(pl,pr))."""
    if isinstance(paddings, int):
        return (paddings, paddings), (paddings, paddings)
    p = list(paddings)
    if len(p) == 1:
        return (p[0], p[0]), (p[0], p[0])
    if len(p) == 2:
        return (p[0], p[0]), (p[1], p[1])
    if len(p) == 4:
        return (p[0], p[2]), (p[1], p[3])
    raise ValueError(f"paddings must have 1, 2 or 4 elements, got {p}")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    (pt, pb), (pl, pr) = _unfold_pads(paddings)
    d = _norm_tuple(dilations, 2)

    def f(v):
        N, C, H, W = v.shape
        patches = jax.lax.conv_general_dilated_patches(
            v, k, s, [(pt, pb), (pl, pr)], rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        L = patches.shape[2] * patches.shape[3]
        return patches.reshape(N, C * k[0] * k[1], L)
    return apply(f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im — inverse of unfold (operators/fold_op): x [N, C*kh*kw, L]
    -> [N, C, H, W] with overlapping patches summed (scatter-add via the
    transpose of the patch-extraction convolution)."""
    out = _norm_tuple(output_sizes, 2)
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    (pt, pb), (pl, pr) = _unfold_pads(paddings)
    d = _norm_tuple(dilations, 2)

    def f(v):
        N, CKK, L = v.shape
        C = CKK // (k[0] * k[1])
        oh = (out[0] + pt + pb - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (out[1] + pl + pr - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = v.reshape(N, C, k[0], k[1], oh, ow)
        # scatter-add each kernel tap into the padded output
        acc = jnp.zeros((N, C, out[0] + pt + pb, out[1] + pl + pr),
                        v.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                ys = i * d[0]
                xs = j * d[1]
                acc = acc.at[:, :, ys:ys + oh * s[0]:s[0],
                             xs:xs + ow * s[1]:s[1]].add(cols[:, :, i, j])
        return acc[:, :, pt:pt + out[0], pl:pl + out[1]]

    return apply(f, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Sampling grid from affine matrices (operators/affine_grid_op):
    theta [N,2,3], out_shape [N,C,H,W] -> grid [N,H,W,2] for grid_sample."""
    if hasattr(out_shape, "numpy"):
        out_shape = [int(x) for x in np.asarray(out_shape.numpy())]
    N, C, H, W = (int(x) for x in out_shape)

    def f(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
        else:
            ys = (jnp.arange(H) * 2 + 1) / H - 1
            xs = (jnp.arange(W) * 2 + 1) / W - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # [H,W,3]
        return jnp.einsum("hwk,nik->nhwi", base,
                          th.astype(jnp.float32)).astype(th.dtype)

    return apply(f, theta)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM channel shift along time (operators/temporal_shift_op):
    x [N*T, C, H, W] -> same shape with the first fold of channels shifted
    back one step in time, the second fold forward."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"data_format must be NCHW or NHWC, got {data_format}")

    def f(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        NT, C, H, W = v.shape
        T = seg_num
        B = NT // T
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        v = v.reshape(B, T, C, H, W)
        back = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])],
                               axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]),
                               v[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([back, fwd, v[:, :, c2:]], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply(f, x)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def f(v, g):
        N, C, H, W = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            ix = (gx + 1) * (W - 1) / 2
            iy = (gy + 1) * (H - 1) / 2
        else:
            ix = ((gx + 1) * W - 1) / 2
            iy = ((gy + 1) * H - 1) / 2

        def sample(img, yy, xx):
            x0 = jnp.floor(xx).astype(jnp.int32)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = xx - x0
            wy = yy - y0

            def get(ix_, iy_):
                inb = (ix_ >= 0) & (ix_ < W) & (iy_ >= 0) & (iy_ < H)
                ic = jnp.clip(ix_, 0, W - 1)
                jc = jnp.clip(iy_, 0, H - 1)
                val = img[:, jc, ic]  # [C, Ho, Wo]
                return jnp.where(inb[None], val, 0.0)

            return (get(x0, y0) * (1 - wx) * (1 - wy)
                    + get(x1, y0) * wx * (1 - wy)
                    + get(x0, y1) * (1 - wx) * wy
                    + get(x1, y1) * wx * wy)

        out = jax.vmap(sample)(v, iy, ix)
        return out
    return apply(f, x, grid)


# alias namespace used by reference code: paddle.nn.functional.common
def linear_compat(*args, **kwargs):
    return linear(*args, **kwargs)


# --------------------------------------------------------------------------
# op-registry tail (COVERAGE.md round-4): direct functional lowerings of
# the remaining reference kernels
# --------------------------------------------------------------------------

def add_position_encoding(x, alpha=1.0, beta=1.0, name=None):
    """alpha*x + beta*PE (operators/add_position_encoding_op.h): first
    half of the feature dim gets sin(pos/10000^(i/half)), second half
    cos, matching the reference's split layout."""
    def f(v):
        B, T, D = v.shape
        half = D // 2
        pos = jnp.arange(T, dtype=v.dtype)[:, None]
        i = jnp.arange(half, dtype=v.dtype)[None, :]
        div = jnp.power(jnp.asarray(10000.0, v.dtype), i / jnp.maximum(half - 1, 1))
        pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], -1)
        if pe.shape[-1] < D:  # odd feature dim: pad last column
            pe = jnp.pad(pe, ((0, 0), (0, D - pe.shape[-1])))
        return alpha * v + beta * pe[None]
    return apply(f, x)


def bilinear(x1, x2, weight, bias=None, name=None):
    """x1^T W x2 per output channel (operators/bilinear_tensor_product_op.h):
    x1 [B,M], x2 [B,N], weight [O,M,N] -> [B,O]."""
    def f(a, b, w, *rest):
        out = jnp.einsum("bm,omn,bn->bo", a, w, b)
        return out + rest[0] if rest else out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply(f, *args)


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking (operators/bpr_loss_op.h): for each
    row, -mean_{j != y} log(sigmoid(x_y - x_j))."""
    def f(x, y):
        B, C = x.shape
        y = y.reshape(-1)  # accept [B] or the paddle-standard [B,1]
        pos = jnp.take_along_axis(x, y[:, None], 1)
        diff = pos - x
        logsig = jax.nn.log_sigmoid(diff)
        mask = jnp.ones_like(x).at[jnp.arange(B), y].set(0)
        return -(logsig * mask).sum(1, keepdims=True) / (C - 1)
    return apply(f, input, label)


def center_loss(input, label, centers, alpha=0.1, update=True, name=None):
    """0.5*||x - c_y||^2 with EMA center updates
    (operators/center_loss_op.h): returns (loss [B,1], new_centers).
    `centers [K,D]` is caller-held state (functional re-design of the
    reference's in-place CenterUpdate)."""
    def f(x, y, c):
        cy = c[y]
        diff = x - cy
        loss = 0.5 * (diff ** 2).sum(1, keepdims=True)
        if not update:
            return loss, c
        cnt = jnp.zeros((c.shape[0],), x.dtype).at[y].add(1.0)
        upd = jnp.zeros_like(c).at[y].add(diff)
        new_c = c + alpha * upd / (cnt[:, None] + 1.0)
        return loss, new_c
    return apply(f, input, label, centers, _multi_out=True)


def conv_shift(x, y, name=None):
    """Circular correlation (operators/conv_shift_op.cc): x [B,N],
    y [B,M] (M odd, M<=N) -> out[b,i] = sum_j x[b,(i+j-M//2) mod N]*y[b,j]."""
    def f(a, b):
        N, M = a.shape[1], b.shape[1]
        i = jnp.arange(N)[:, None]
        j = jnp.arange(M)[None, :]
        src = (i + j - M // 2) % N
        return jnp.einsum("bnm,bm->bn", a[:, src], b)
    return apply(f, x, y)


def ctc_align(ids, input_length, blank=0, merge_repeated=True, name=None):
    """CTC greedy-path collapse (operators/ctc_align_op.h): merge repeats
    then drop blanks; output packed left, zero-padded, plus new lens."""
    def f(v, ln):
        B, T = v.shape
        ln = ln.reshape(-1)  # accept [B] or the paddle-standard [B,1]
        t = jnp.arange(T)[None, :]
        valid = t < ln[:, None]
        if merge_repeated:
            first = jnp.concatenate(
                [jnp.ones((B, 1), bool), v[:, 1:] != v[:, :-1]], 1)
        else:
            first = jnp.ones((B, T), bool)
        keep = valid & first & (v != blank)
        order = jnp.argsort(jnp.where(keep, t, T + t), axis=1)
        packed = jnp.take_along_axis(v, order, axis=1)
        new_len = keep.sum(1)
        packed = jnp.where(t < new_len[:, None], packed, 0)
        return packed, new_len
    return apply(f, ids, input_length, _multi_out=True)


def hinge_loss(logits, labels, name=None):
    """max(0, 1 - (2y-1)*x) (operators/hinge_loss_op.h), labels in {0,1}."""
    return apply(lambda x, y: jnp.maximum(
        0.0, 1.0 - (2.0 * y - 1.0) * x), logits, labels)


def log_loss(input, label, epsilon=1e-4, name=None):
    """-(y log(p+eps) + (1-y) log(1-p+eps)) (operators/log_loss_op.h)."""
    return apply(lambda p, y: -y * jnp.log(p + epsilon)
                 - (1.0 - y) * jnp.log(1.0 - p + epsilon), input, label)


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (operators/rank_loss_op.h):
    log(1+exp(o)) - y*o with o = left - right."""
    return apply(lambda y, a, b: jnp.logaddexp(0.0, a - b) - y * (a - b),
                 label, left, right)


def row_conv(x, weight, name=None):
    """Lookahead convolution (operators/row_conv_op.h): x [B,T,D],
    weight [k+1,D] -> out[t] = sum_{j=0..k} x[t+j]*w[j] (zeros past T)."""
    def f(v, w):
        B, T, D = v.shape
        K = w.shape[0]
        t = jnp.arange(T)[None, :, None]
        j = jnp.arange(K)[None, None, :]
        src = t + j
        valid = src < T
        g = v[jnp.arange(B)[:, None, None], jnp.clip(src, 0, T - 1)]
        g = jnp.where(valid[..., None], g, 0)
        return jnp.einsum("btkd,kd->btd", g, w)
    return apply(f, x, weight)


def spp(x, pyramid_height=3, pool_type="max", name=None):
    """Spatial pyramid pooling (operators/spp_op.h): concat adaptive
    2^l x 2^l poolings, flattened -> [B, C*sum(4^l)]."""
    def f(v):
        outs = []
        for lvl in range(pyramid_height):
            bins = 2 ** lvl
            p = _adaptive_pool2d_impl(v, bins, pool_type)
            outs.append(p.reshape(v.shape[0], -1))
        return jnp.concatenate(outs, axis=1)
    return apply(f, x)


def _adaptive_pool2d_impl(v, bins, pool_type):
    # floor-start / ceil-end bins — the same convention as
    # adaptive_avg_pool2d above and the reference spp_op.h
    # (kernel = ceil(dim/bins)), so non-divisible sizes agree
    B, C, H, W = v.shape
    rows = []
    for i in range(bins):
        h0, h1 = (i * H) // bins, -(-((i + 1) * H) // bins)
        cols = []
        for j in range(bins):
            w0, w1 = (j * W) // bins, -(-((j + 1) * W) // bins)
            cell = v[:, :, h0:h1, w0:w1]
            red = cell.max((2, 3)) if pool_type == "max" else cell.mean((2, 3))
            cols.append(red)
        rows.append(jnp.stack(cols, -1))
    return jnp.stack(rows, -2)  # [B,C,bins,bins]


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, name=None):
    """Inverse of max_pool2d-with-index (operators/unpool_op.h): scatter
    pooled values back to their argmax flat positions."""
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else ((stride, stride)
                                    if isinstance(stride, int)
                                    else tuple(stride))

    def f(v, idx):
        B, C, H, W = v.shape
        if output_size is not None:
            oh, ow = output_size[-2], output_size[-1]
        else:
            oh = (H - 1) * st[0] + ks[0] - 2 * padding
            ow = (W - 1) * st[1] + ks[1] - 2 * padding
        flat = jnp.zeros((B, C, oh * ow), v.dtype)
        out = flat.at[
            jnp.arange(B)[:, None, None],
            jnp.arange(C)[None, :, None],
            idx.reshape(B, C, -1)].set(v.reshape(B, C, -1), mode="drop")
        return out.reshape(B, C, oh, ow)
    return apply(f, x, indices)
