"""paddle.nn.functional.activation — submodule alias re-exporting the reference
module's names (python/paddle/nn/functional/activation.py __all__) from the
flat functional surface."""

from . import (  # noqa: F401
    brelu, elu, gelu, hardshrink, hardsigmoid, hardswish, hardtanh,
    leaky_relu, log_sigmoid, log_softmax, maxout, prelu, relu, relu6,
    selu, sigmoid, softmax, softplus, softshrink, softsign, swish,
    tanh, tanhshrink, thresholded_relu)

__all__ = ['brelu', 'elu', 'gelu', 'hardshrink', 'hardsigmoid', 'hardswish', 'hardtanh', 'leaky_relu', 'log_sigmoid', 'log_softmax', 'maxout', 'prelu', 'relu', 'relu6', 'selu', 'sigmoid', 'softmax', 'softplus', 'softshrink', 'softsign', 'swish', 'tanh', 'tanhshrink', 'thresholded_relu']
