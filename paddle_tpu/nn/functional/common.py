"""paddle.nn.functional.common — submodule alias re-exporting the reference
module's names (python/paddle/nn/functional/common.py __all__) from the
flat functional surface."""

from . import (  # noqa: F401
    alpha_dropout, bilinear, cosine_similarity, dropout, dropout2d,
    dropout3d, interpolate, label_smooth, linear, pad, unfold,
    upsample)

__all__ = ['alpha_dropout', 'bilinear', 'cosine_similarity', 'dropout', 'dropout2d', 'dropout3d', 'interpolate', 'label_smooth', 'linear', 'pad', 'unfold', 'upsample']
