"""paddle.nn.functional.conv — submodule alias re-exporting the reference
module's names (python/paddle/nn/functional/conv.py __all__) from the
flat functional surface."""

from . import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose)

__all__ = ['conv1d', 'conv1d_transpose', 'conv2d', 'conv2d_transpose', 'conv3d', 'conv3d_transpose']
