"""paddle.nn.functional.extension — submodule alias re-exporting the reference
module's names (python/paddle/nn/functional/extension.py __all__) from the
flat functional surface."""

from . import (  # noqa: F401
    diag_embed)

__all__ = ['diag_embed']
