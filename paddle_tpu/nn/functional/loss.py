"""paddle.nn.functional.loss — submodule alias re-exporting the reference
module's names (python/paddle/nn/functional/loss.py __all__) from the
flat functional surface."""

from . import (  # noqa: F401
    binary_cross_entropy, binary_cross_entropy_with_logits,
    cross_entropy, ctc_loss, dice_loss, hsigmoid_loss, kl_div,
    l1_loss, log_loss, margin_ranking_loss, mse_loss, nll_loss,
    npair_loss, sigmoid_focal_loss, smooth_l1_loss,
    softmax_with_cross_entropy, square_error_cost)

__all__ = ['binary_cross_entropy', 'binary_cross_entropy_with_logits', 'cross_entropy', 'ctc_loss', 'dice_loss', 'hsigmoid_loss', 'kl_div', 'l1_loss', 'log_loss', 'margin_ranking_loss', 'mse_loss', 'nll_loss', 'npair_loss', 'sigmoid_focal_loss', 'smooth_l1_loss', 'softmax_with_cross_entropy', 'square_error_cost']
