"""paddle.nn.functional.pooling — submodule alias re-exporting the reference
module's names (python/paddle/nn/functional/pooling.py __all__) from the
flat functional surface."""

from . import (  # noqa: F401
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
    avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d, max_pool2d,
    max_pool3d)

__all__ = ['adaptive_avg_pool1d', 'adaptive_avg_pool2d', 'adaptive_avg_pool3d', 'adaptive_max_pool1d', 'adaptive_max_pool2d', 'adaptive_max_pool3d', 'avg_pool1d', 'avg_pool2d', 'avg_pool3d', 'max_pool1d', 'max_pool2d', 'max_pool3d']
