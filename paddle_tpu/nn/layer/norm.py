"""Normalization layers. Reference: python/paddle/nn/layer/norm.py over
batch_norm_op.cc / layer_norm_op.cu / group_norm_op.cc / instance_norm_op.cc.

BatchNorm keeps running stats in buffers; under functional_call the updated
stats come back as new_buffers (the jit path), eagerly they are written
in-place — same forward code for both, per layer_base design.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor import Tensor, _is_tracer
from ..layer_base import Layer
from .. import functional as F
from .. import initializer as I


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean",
                             Tensor(jnp.zeros([num_features],
                                              jnp.float32)),
                             persistable=True)
        self.register_buffer("_variance",
                             Tensor(jnp.ones([num_features],
                                             jnp.float32)),
                             persistable=True)

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm(num_channels) — accepts act for parity."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, use_global_stats=False,
                 **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCW" if data_format in ("NCL",) else data_format,
                         use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats inside a pjit'd step are computed over the global
    (sharded) batch automatically when the reduction spans the data axis —
    XLA inserts the cross-replica sums (sync_batch_norm_pass analog).  Eagerly
    it equals BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for l in layer.sublayers(include_self=True):
            for name, sub in list(l._sub_layers.items()):
                if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                    nb = SyncBatchNorm(sub._num_features, sub._momentum,
                                       sub._epsilon,
                                       data_format=sub._data_format)
                    nb.weight.set_value(sub.weight)
                    nb.bias.set_value(sub.bias)
                    nb._mean.set_value(sub._mean)
                    nb._variance.set_value(sub._variance)
                    l._sub_layers[name] = nb
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(self._normalized_shape,
                                              attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    _nd = 3

    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    _nd = 4


class InstanceNorm3D(InstanceNorm1D):
    _nd = 5


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral normalization: forward(weight) returns weight / sigma_max,
    sigma_max estimated by `power_iters` rounds of power iteration on the
    matricized weight (dim moved first). Persistent u/v buffers carry the
    iteration across steps (reference: spectral_norm_op.cc + fluid
    nn.SpectralNorm; TPU form: pure jnp matvecs, stop_gradient'd u/v as the
    reference's grad kernel treats them as constants)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim = int(dim)
        self._power_iters = int(power_iters)
        self._eps = float(eps)
        self._shape = list(weight_shape)
        h = int(weight_shape[self._dim])
        w = int(np.prod(weight_shape)) // h
        self.register_buffer(
            "weight_u", Tensor(jax.random.normal(
                jax.random.PRNGKey(0), (h,), jnp.float32)), persistable=True)
        self.register_buffer(
            "weight_v", Tensor(jax.random.normal(
                jax.random.PRNGKey(1), (w,), jnp.float32)), persistable=True)

    def forward(self, weight):
        from ...tensor import Tensor as T, apply, unwrap

        dim, eps, iters = self._dim, self._eps, self._power_iters
        ndim = len(self._shape)
        perm = [dim] + [i for i in range(ndim) if i != dim]

        def f(w, u, v):
            wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
            wm32 = wm.astype(jnp.float32)
            for _ in range(iters):
                v = wm32.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm32 @ v
                u = u / (jnp.linalg.norm(u) + eps)
            u = jax.lax.stop_gradient(u)
            v = jax.lax.stop_gradient(v)
            sigma = u @ (wm32 @ v)
            return (w / sigma.astype(w.dtype)), u, v

        out, u_new, v_new = apply(f, weight, self.weight_u, self.weight_v,
                                  _multi_out=True)
        if not _is_tracer(unwrap(weight)):
            self.weight_u.set_value(unwrap(u_new))
            self.weight_v.set_value(unwrap(v_new))
        return out
