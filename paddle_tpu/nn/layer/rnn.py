"""Recurrent layers. Reference: python/paddle/nn/layer/rnn.py over
lstm/gru/cudnn_lstm ops (operators/rnn_op, cudnn_lstm_op.cu).

TPU-native: the whole sequence recurrence is ONE `lax.scan` inside a single
apply() — XLA compiles the loop body once; no per-timestep python dispatch,
and the scan differentiates through cleanly on the tape (the cudnn_lstm
analog).  Gate order is [i, f, g, o] (LSTM) / [r, z, n] (GRU), matching the
reference kernels.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor import Tensor, apply
from ..layer_base import Layer
from .. import functional as F
from .. import initializer as I


def _lstm_step(params, h, c, x):
    w_ih, w_hh, b_ih, b_hh = params
    gates = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(params, h, x):
    w_ih, w_hh, b_ih, b_hh = params
    gi = x @ w_ih.T + b_ih
    gh = h @ w_hh.T + b_hh
    ri, zi, ni = jnp.split(gi, 3, axis=-1)
    rh, zh, nh = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ri + rh)
    z = jax.nn.sigmoid(zi + zh)
    n = jnp.tanh(ni + r * nh)
    return (1 - z) * n + z * h


def _rnn_step(params, h, x, activation):
    w_ih, w_hh, b_ih, b_hh = params
    a = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    return jnp.tanh(a) if activation == "tanh" else jax.nn.relu(a)


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, gate_mult, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [gate_mult * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [gate_mult * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [gate_mult * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [gate_mult * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def get_initial_states(self, batch_size, dtype=None):
        from ...framework.dtype import convert_dtype

        # default to the cell's parameter dtype: an f32 initial state
        # would silently upcast every gate matmul under bf16 (same
        # failure mode as the attention decode cache)
        if dtype is None:
            dt = self.weight_hh.value.dtype
        else:
            dt = convert_dtype(dtype) or jnp.float32
        return Tensor(jnp.zeros([batch_size, self.hidden_size], dt))


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 4, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            b = inputs.shape[0]
            states = (self.get_initial_states(b), self.get_initial_states(b))
        h, c = states

        def f(x, h_, c_, wi, wh, bi, bh):
            return _lstm_step((wi, wh, bi, bh), h_, c_, x)

        h_new, c_new = apply(f, inputs, h, c, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh, _multi_out=True)
        return h_new, (h_new, c_new)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 3, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0])
        h = states

        def f(x, h_, wi, wh, bi, bh):
            return _gru_step((wi, wh, bi, bh), h_, x)

        h_new = apply(f, inputs, h, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh)
        return h_new, h_new


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 1, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0])
        h = states

        def f(x, h_, wi, wh, bi, bh):
            return _rnn_step((wi, wh, bi, bh), h_, x, self.activation)

        h_new = apply(f, inputs, h, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh)
        return h_new, h_new


class RNN(Layer):
    """Run a cell over a sequence (python loop — use LSTM/GRU classes for the
    fused scan path)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import tensor_ops as T

        if not self.time_major:
            inputs = T.transpose(inputs, [1, 0, 2])
        steps = range(inputs.shape[0])
        if self.is_reverse:
            steps = reversed(list(steps))
        states = initial_states
        outs = []
        for t in steps:
            out, states = self.cell(inputs[t], states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        out_seq = T.stack(outs, axis=0)
        if not self.time_major:
            out_seq = T.transpose(out_seq, [1, 0, 2])
        return out_seq, states


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrence via lax.scan."""

    MODE = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.num_directions = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[self.MODE]
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 \
                    else hidden_size * self.num_directions
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                self.add_parameter(
                    f"weight_ih{sfx}",
                    self.create_parameter([gate_mult * hidden_size, in_sz],
                                          default_initializer=init))
                self.add_parameter(
                    f"weight_hh{sfx}",
                    self.create_parameter([gate_mult * hidden_size, hidden_size],
                                          default_initializer=init))
                self.add_parameter(
                    f"bias_ih{sfx}",
                    self.create_parameter([gate_mult * hidden_size],
                                          default_initializer=init, is_bias=True))
                self.add_parameter(
                    f"bias_hh{sfx}",
                    self.create_parameter([gate_mult * hidden_size],
                                          default_initializer=init, is_bias=True))

    def _layer_params(self, layer, d):
        sfx = f"_l{layer}" + ("_reverse" if d else "")
        return (self._parameters[f"weight_ih{sfx}"],
                self._parameters[f"weight_hh{sfx}"],
                self._parameters[f"bias_ih{sfx}"],
                self._parameters[f"bias_hh{sfx}"])

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import tensor_ops as T

        is_lstm = self.MODE == "LSTM"
        L, D, H = self.num_layers, self.num_directions, self.hidden_size
        batch_axis = 1 if self.time_major else 0
        B = inputs.shape[batch_axis]

        if initial_states is None:
            z = Tensor(jnp.zeros([L * D, B, H], dtype=inputs.dtype))
            initial_states = (z, z.clone()) if is_lstm else z

        mode = self.MODE

        def run(x, h0, c0, *flat_params):
            # x: [B,S,I] or [S,B,I] -> time-major [S,B,I]
            if not self.time_major:
                x = jnp.swapaxes(x, 0, 1)
            params = [flat_params[i * 4:(i + 1) * 4]
                      for i in range(L * D)]
            h_outs, c_outs = [], []
            for layer in range(L):
                dir_outs = []
                for d in range(D):
                    p = params[layer * D + d]
                    xs = jnp.flip(x, 0) if d else x
                    h_init = h0[layer * D + d]
                    c_init = c0[layer * D + d] if is_lstm else None

                    if mode == "LSTM":
                        def step(carry, xt, p=p):
                            h_, c_ = carry
                            hn, cn = _lstm_step(p, h_, c_, xt)
                            return (hn, cn), hn
                        (hT, cT), ys = jax.lax.scan(step, (h_init, c_init), xs)
                        c_outs.append(cT)
                    elif mode == "GRU":
                        def step(h_, xt, p=p):
                            hn = _gru_step(p, h_, xt)
                            return hn, hn
                        hT, ys = jax.lax.scan(step, h_init, xs)
                    else:
                        act = "tanh" if mode == "RNN_TANH" else "relu"

                        def step(h_, xt, p=p, act=act):
                            hn = _rnn_step(p, h_, xt, act)
                            return hn, hn
                        hT, ys = jax.lax.scan(step, h_init, xs)
                    h_outs.append(hT)
                    if d:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                x = jnp.concatenate(dir_outs, axis=-1) if D > 1 else dir_outs[0]
            out = x if self.time_major else jnp.swapaxes(x, 0, 1)
            hs = jnp.stack(h_outs, 0)
            cs = jnp.stack(c_outs, 0) if is_lstm else jnp.zeros_like(hs)
            return out, hs, cs

        flat = []
        for layer in range(L):
            for d in range(D):
                flat.extend(self._layer_params(layer, d))
        h0, c0 = (initial_states if is_lstm else (initial_states, initial_states))
        out, hs, cs = apply(run, inputs, h0, c0, *flat, _multi_out=True)
        if is_lstm:
            return out, (hs, cs)
        return out, hs


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        self.MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import tensor_ops as T

        if initial_states is None:
            initial_states = (None, None)
        out_fw, st_fw = self.rnn_fw(inputs, initial_states[0])
        out_bw, st_bw = self.rnn_bw(inputs, initial_states[1])
        return T.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
