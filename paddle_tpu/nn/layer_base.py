"""nn.Layer: the module base class, plus the functional bridge to jax.jit.

Reference parity: python/paddle/fluid/dygraph/layers.py (Layer — parameters,
sublayers, hooks, state_dict) and framework.py ParamAttr/Parameter (:5244).

TPU-native twist: a Layer is simultaneously
  (a) an eager stateful module (paddle dygraph UX: params are attributes,
      forward mutates running stats, loss.backward() works), and
  (b) a pure function of its parameters via `functional_call`, which swaps
      traced arrays into the Parameter slots and runs the same forward code
      under jax tracing.  This is what lets jax.jit/pjit compile whole train
      steps without an AST translator (the reference needs
      dygraph_to_static/program_translator.py:233 for this; here tracing IS
      the execution model).
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Iterator

import jax
import numpy as np

from .. import autograd
from ..framework import random as _random
from ..framework.dtype import convert_dtype, get_default_dtype
from ..tensor import Tensor
from . import initializer as I


class Parameter(Tensor):
    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable, name=name)
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def set_value(self, value):
        v = value.value if isinstance(value, Tensor) else jax.numpy.asarray(value)
        self._value = v.astype(self.dtype) if v.dtype != self.dtype else v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# give plain Tensors set_value too (used for buffers)
def _tensor_set_value(self, value):
    v = value.value if isinstance(value, Tensor) else jax.numpy.asarray(value)
    self._value = v


Tensor.set_value = _tensor_set_value


class ParamAttr:
    """Reference parity: python/paddle/fluid/param_attr.py ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"Cannot make ParamAttr from {attr!r}")


_name_counters: dict[str, int] = {}


def _unique_name(prefix: str) -> str:
    n = _name_counters.get(prefix, 0)
    _name_counters[prefix] = n + 1
    return f"{prefix}_{n}"


class Layer:
    def __init__(self, name_scope: str | None = None, dtype="float32"):
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._full_name = _unique_name(name_scope or type(self).__name__.lower())
        self._parameters: OrderedDict[str, Parameter] = OrderedDict()
        self._sub_layers: OrderedDict[str, "Layer"] = OrderedDict()
        self._buffers: OrderedDict[str, Tensor] = OrderedDict()
        self._non_persistable_buffer_names: set[str] = set()
        self._forward_pre_hooks: OrderedDict[int, Callable] = OrderedDict()
        self._forward_post_hooks: OrderedDict[int, Callable] = OrderedDict()

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if params is not None and isinstance(value, Parameter):
            for d in (subs, bufs):
                d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif subs is not None and isinstance(value, Layer):
            for d in (params, bufs):
                d.pop(name, None)
            subs[name] = value
            self.__dict__.pop(name, None)
        elif bufs is not None and isinstance(value, Tensor):
            for d in (params, subs):
                d.pop(name, None)
            bufs[name] = value
            self._non_persistable_buffer_names.add(name)
            self.__dict__.pop(name, None)
        else:
            for d in (params, subs, bufs):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # -- construction helpers ---------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter | None:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer or \
            (I.Constant(0.0) if is_bias else I.XavierUniform())
        value = init(shape, dtype)
        p = Parameter(value, name=attr.name or _unique_name("param"),
                      trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            self._non_persistable_buffer_names.discard(name)
        return tensor

    def create_tensor(self, name=None, persistable=False, dtype=None):
        import jax.numpy as jnp

        t = Tensor(jnp.zeros([], convert_dtype(dtype) or self._dtype))
        if name:
            self.register_buffer(name, t, persistable)
        return t

    # -- traversal ---------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False, layers_set=None
                        ) -> Iterator[tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=p, include_self=True,
                                           layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return list(self._sub_layers.values())

    def named_children(self):
        return list(self._sub_layers.items())

    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[tuple[str, Parameter]]:
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{layer_name}.{pname}" if layer_name else pname), p

    def parameters(self, include_sublayers=True) -> list[Parameter]:
        return [p for _, p in self.named_parameters()]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{layer_name}.{bname}" if layer_name else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers()]

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- mode --------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix):
            short = name.rsplit(".", 1)[-1]
            owner = self
            if "." in name:
                # find owning layer to check persistability
                path = name.rsplit(".", 1)[0]
                for ln, l in self.named_sublayers(include_self=True):
                    if ln == path:
                        owner = l
                        break
            if short not in owner._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            v = value.value if isinstance(value, Tensor) else np.asarray(value)
            if tuple(np.shape(v)) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: loaded {np.shape(v)} vs "
                    f"{tuple(target.shape)}")
            target.set_value(v)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        h = _HookRemoveHelper(self._forward_pre_hooks, hook)
        return h

    def register_forward_post_hook(self, hook):
        h = _HookRemoveHelper(self._forward_post_hooks, hook)
        return h

    # -- execution -----------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    # -- dtype / device conversion -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self.astype(dtype)
        return self

    def astype(self, dtype):
        dt = convert_dtype(dtype)
        for p in self.parameters():
            p._value = p._value.astype(dt)
        for b in self.buffers():
            if jax.numpy.issubdtype(b.dtype, jax.numpy.floating):
                b._value = b._value.astype(dt)
        for l in self.sublayers(include_self=True):
            l._dtype = dt
        return self

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def full_name(self):
        return self._full_name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"

    @contextlib.contextmanager
    def no_sync(self):
        yield  # DataParallel grad-sync pause: a no-op outside DP


class _HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks_dict, hook):
        self._hooks = hooks_dict
        self._id = _HookRemoveHelper._next_id
        _HookRemoveHelper._next_id += 1
        hooks_dict[self._id] = hook

    def remove(self):
        self._hooks.pop(self._id, None)


# ---------------------------------------------------------------------------
# functional bridge: Layer -> pure function of params (the jit path)
# ---------------------------------------------------------------------------
def state_pytrees(layer: Layer):
    """Extract (params, buffers) as flat {name: jax.Array} dicts."""
    params = {k: p.value for k, p in layer.named_parameters()}
    buffers = {k: b.value for k, b in layer.named_buffers()}
    return params, buffers


@contextlib.contextmanager
def _swapped_state(layer: Layer, params: dict | None, buffers: dict | None):
    saved: list[tuple[Tensor, Any]] = []
    pmap = dict(layer.named_parameters())
    bmap = dict(layer.named_buffers())
    try:
        for name, val in (params or {}).items():
            t = pmap[name]
            saved.append((t, t._value))
            t._value = val
        # snapshot ALL buffers: forward may rebind them (running stats) and a
        # traced value must never leak into eager layer state
        for name, t in bmap.items():
            saved.append((t, t._value))
            if buffers and name in buffers:
                t._value = buffers[name]
        yield bmap
    finally:
        for t, old in saved:
            t._value = old


def functional_call(layer: Layer, params: dict | None, args=(), kwargs=None,
                    buffers: dict | None = None, rng=None, mutable: bool = True,
                    method: str | None = None):
    """Run layer.forward with `params`/`buffers` substituted, returning
    (output, new_buffers).  Safe to call inside jax.jit/grad tracing: the
    tape is suspended and randomness must come from `rng`.
    `method` names an alternate entry point (e.g. GPTForCausalLM.loss, the
    chunked LM-head path) — called directly, so forward hooks are skipped.
    """
    kwargs = kwargs or {}
    ctx = _random.rng_guard(rng) if rng is not None else contextlib.nullcontext()
    with autograd.suspend_tape(), ctx, _swapped_state(layer, params, buffers) as bmap:
        fn = layer if method is None else getattr(layer, method)
        out = fn(*args, **kwargs)
        new_buffers = {k: t.value for k, t in bmap.items()} if mutable else None
    return out, new_buffers
