"""Weight normalization as a forward-pre-hook reparameterization.

Reference parity: python/paddle/nn/utils/weight_norm_hook.py
(weight_norm:155, remove_weight_norm:202): `weight` is replaced by
magnitude `weight_g` and direction `weight_v`, recombined as
w = g * v / ||v|| before every forward.  ||v|| is computed over all
dims except `dim` (dim=None -> whole-tensor norm).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...tensor import Tensor, apply, unwrap
from ..layer_base import Parameter

__all__ = ["weight_norm", "remove_weight_norm"]


def _norm_except_dim(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v)))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def _recompute(g, v, dim):
    def f(gv, vv):
        n = _norm_except_dim(vv, dim)
        return gv * vv / jnp.maximum(n, 1e-12)

    return apply(f, g, v)


class WeightNorm:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def __call__(self, layer, inputs):
        g = layer._parameters[self.name + "_g"]
        v = layer._parameters[self.name + "_v"]
        w = _recompute(g, v, self.dim)
        # plain object attribute: bypasses Layer.__setattr__ so the
        # recomputed tensor is not registered as a buffer/parameter
        object.__setattr__(layer, self.name, w)
        return None


def weight_norm(layer, name="weight", dim=0):
    if name + "_g" in layer._parameters:
        raise ValueError(f"weight_norm already applied to {name!r}")
    w = layer._parameters.get(name)
    if w is None:
        raise ValueError(f"{type(layer).__name__} has no parameter {name!r}")
    w_val = unwrap(w)
    g0 = np.asarray(_norm_except_dim(w_val, dim))
    del layer._parameters[name]
    layer.add_parameter(name + "_g", Parameter(jnp.asarray(g0),
                                               name=f"{name}_g"))
    layer.add_parameter(name + "_v", Parameter(w_val, name=f"{name}_v"))
    fn = WeightNorm(name, dim)
    handle = layer.register_forward_pre_hook(fn)
    fn._handle = handle
    hooks = getattr(layer, "_weight_norm_hooks", {})
    hooks[name] = fn
    object.__setattr__(layer, "_weight_norm_hooks", hooks)
    # materialize once so layer.<name> exists before the first forward
    fn(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    hooks = getattr(layer, "_weight_norm_hooks", {})
    fn = hooks.pop(name, None)
    if fn is None:
        raise ValueError(f"weight_norm of {name!r} not found on "
                         f"{type(layer).__name__}")
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    w = _recompute(g, v, fn.dim)
    if hasattr(layer, name):
        try:
            object.__delattr__(layer, name)
        except AttributeError:
            pass
    fn._handle.remove()
    layer.add_parameter(name, Parameter(unwrap(w), name=name))
    return layer
