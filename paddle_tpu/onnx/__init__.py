"""paddle.onnx — ONNX export.

Reference surface: python/paddle/onnx/export.py (delegates to paddle2onnx
over a traced ProgramDesc).  TPU-native design: trace the Layer's
eval-mode forward to a jaxpr (weights close over as constants) and map
each primitive to standard ONNX ops — no intermediate ProgramDesc, no
external converter.  The artifact is a spec-conformant ModelProto written
with a dependency-free protobuf codec (proto.py) and validated in-tree by
round-trip execution (runtime.py), since this image ships neither `onnx`
nor `onnxruntime`.

StableHLO via paddle_tpu.inference.save_inference_model remains the
TPU-serving artifact; ONNX export exists for interchange with the wider
runtime ecosystem, like the reference's paddle2onnx path.
"""
from __future__ import annotations

import numpy as np

from . import proto
from .convert import GraphBuilder, UnsupportedOnnxOp, _widen, convert_jaxpr
from .runtime import ONNXModel

__all__ = ["export", "ONNXModel", "UnsupportedOnnxOp"]


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export ``layer`` to ``<path>.onnx``; returns the written filename.

    ``input_spec``: list of InputSpec / Tensors / ndarrays describing the
    inputs.  ``configs['example_inputs']`` may carry concrete example
    arrays when input_spec holds symbolic (-1/None) dims.

    Symbolic (-1/None) InputSpec dims export as TRUE dynamic dims: the
    forward is traced with jax shape polymorphism and every shape the
    graph computes with (Reshape/Expand targets) is emitted as runtime
    Shape/Gather/Concat values, so one artifact serves any size there —
    all dynamic dims share one symbol (the batch), matching the
    StableHLO path's contract.  Without symbolic dims the graph is
    shape-specialized at the example sizes.  Matches the reference
    signature (python/paddle/onnx/export.py:30); ``opset_version`` below
    13 is promoted to 13 (the emitted op set).
    """
    import jax

    from ..nn.layer_base import Layer, functional_call, state_pytrees
    from ..tensor import Tensor

    if not isinstance(layer, Layer):
        raise TypeError(f"export expects a Layer, got {type(layer)}")
    # emitted graph uses opset-13..17 op forms (e.g. ReduceMax axes as an
    # attribute, which opset 18 moved to an input) — clamp both ends so
    # the declared opset always matches what the nodes actually are
    opset_version = min(max(int(opset_version), 13), 17)

    examples = configs.get("example_inputs")
    if examples is None:
        if input_spec is None:
            raise ValueError("export needs input_spec or example_inputs")
        examples = []
        for s in input_spec:
            if isinstance(s, Tensor):
                examples.append(np.asarray(s.numpy()))
            elif isinstance(s, np.ndarray):
                examples.append(s)
            else:  # InputSpec: trace symbolic (-1/None) dims at 1
                shape = [1 if (d is None or int(d) < 0) else int(d)
                         for d in s.shape]
                examples.append(np.zeros(shape, np.dtype(s.dtype)))
    examples = [np.asarray(e.numpy() if isinstance(e, Tensor) else e)
                for e in examples]

    # dynamic dims: trace with jax shape polymorphism; one shared symbol
    # for every -1/None axis (independent dynamic sizes would need the
    # model to never relate them — re-export per shape for that case)
    input_names = [f"x{i}" for i in range(len(examples))]
    sym_sources = {}
    trace_args = list(examples)
    if input_spec is not None and any(
            not isinstance(s, (Tensor, np.ndarray))
            and any(d is None or int(d) < 0 for d in s.shape)
            for s in input_spec):
        from jax import export as jexport

        bsym, = jexport.symbolic_shape("b")
        trace_args = []
        for i, (s, ex) in enumerate(zip(input_spec, examples)):
            if isinstance(s, (Tensor, np.ndarray)):
                trace_args.append(ex)
                continue
            shape = []
            for ax, d in enumerate(s.shape):
                if d is None or int(d) < 0:
                    shape.append(bsym)
                    sym_sources.setdefault(
                        str(bsym), (bsym, input_names[i], ax))
                else:
                    shape.append(int(d))
            trace_args.append(jax.ShapeDtypeStruct(tuple(shape), ex.dtype))

    was_training = layer.training
    layer.eval()
    try:
        params, buffers = state_pytrees(layer)

        def fwd(*xs):
            out, _ = functional_call(layer, params,
                                     [Tensor(x) for x in xs],
                                     buffers=buffers, mutable=False)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            return tuple(o.value if isinstance(o, Tensor) else o
                         for o in outs)

        closed = jax.make_jaxpr(fwd)(*trace_args)
    finally:
        if was_training:
            layer.train()

    g = GraphBuilder()
    g.sym_sources = sym_sources
    g, out_names = convert_jaxpr(closed, input_names, g)

    # graph outputs must be node outputs, not raw initializers/inputs
    final, seen = [], set()
    for nm in out_names:
        if nm in input_names or nm in seen or nm in g.init_names:
            nm = g.add("Identity", [nm])
        final.append(nm)
        seen.add(nm)

    def _dims(shape):
        return [int(d) if isinstance(d, (int, np.integer)) else str(d)
                for d in shape]

    in_vis = [proto.value_info(nm, _widen(ta.dtype), _dims(ta.shape))
              for nm, ta in zip(input_names, trace_args)]
    out_vis = [proto.value_info(nm, _widen(v.aval.dtype),
                                _dims(v.aval.shape))
               for nm, v in zip(final, closed.jaxpr.outvars)]

    graph = proto.graph(g.nodes, "paddle_tpu_graph", in_vis, out_vis,
                        g.initializers)
    blob = proto.model(graph, opset_version)
    fname = path + ".onnx"
    with open(fname, "wb") as f:
        f.write(blob)
    return fname
