"""paddle.onnx — export surface (reference python/paddle/onnx/export.py
delegates to paddle2onnx)."""


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """ONNX export is not part of the TPU build: the serving artifact is
    StableHLO via paddle_tpu.inference.save_inference_model /
    paddle_tpu.static.save_inference_model (jax.export) — the
    TPU-compilable exchange format.  COVERAGE.md documents the
    disposition; convert StableHLO downstream if ONNX is required."""
    raise NotImplementedError(export.__doc__)
