"""jaxpr -> ONNX GraphProto conversion.

The exporter traces a Layer's eval-mode forward to a jaxpr (params closed
over -> graph initializers) and maps each equation to standard ONNX ops
(target opset 13).  This replaces the reference's paddle2onnx delegation
(python/paddle/onnx/export.py) with a direct trace-based converter — the
same architectural role paddle2onnx's ProgramDesc walker plays, built on
jaxpr instead.

Unsupported primitives raise UnsupportedOnnxOp naming the primitive and
the layer path, so a failed export is attributable rather than silently
wrong.  bfloat16 is widened to float32 (ONNX runtimes' common denominator).
"""
from __future__ import annotations

import numpy as np

from . import proto

INT64_MIN = -(1 << 63)


class UnsupportedOnnxOp(NotImplementedError):
    pass


def _np(x):
    arr = np.asarray(x)
    if str(arr.dtype) == "bfloat16":  # widen: ONNX runtimes' common ground
        arr = arr.astype(np.float32)
    return arr


def _widen(dt) -> np.dtype:
    return np.dtype(np.float32) if str(dt) == "bfloat16" else np.dtype(dt)


class GraphBuilder:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self._names = {}
        self._n = 0
        self._const_cache = {}
        self.init_names = set()
        # symbolic-dim support (dynamic batch): str(sym) -> (sym_obj,
        # graph_input_name, axis).  Filled by export() when tracing with
        # jax.export.symbolic_shape; _dyn_dim turns a symbol into a
        # runtime int64[1] value via Shape+Gather on the source input.
        self.sym_sources = {}
        self._dyn_cache = {}
        self._shape_vec_cache = {}

    def fresh(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def add(self, op_type, inputs, n_out=1, **attrs):
        outs = [self.fresh(op_type.lower()) for _ in range(n_out)]
        self.nodes.append(proto.node(op_type, inputs, outs,
                                     name=self.fresh(op_type), **attrs))
        return outs if n_out > 1 else outs[0]

    def const(self, arr, hint="c"):
        """Register a constant as an initializer; dedup small ones."""
        arr = _np(arr)
        key = None
        if arr.size <= 64:
            key = (str(arr.dtype), arr.shape, arr.tobytes())
            if key in self._const_cache:
                return self._const_cache[key]
        name = self.fresh(hint)
        self.initializers.append(proto.tensor(name, arr))
        self.init_names.add(name)
        if key is not None:
            self._const_cache[key] = name
        return name

    def i64(self, values, hint="shape"):
        return self.const(np.asarray(values, np.int64), hint)

    def _dyn_dim(self, d):
        """int64[1] runtime value for a symbolic dimension (or sym*k)."""
        key = str(d)
        if key in self._dyn_cache:
            return self._dyn_cache[key]
        src = self.sym_sources.get(key)
        if src is not None:
            _, inp, ax = src
            shp = self.add("Shape", [inp])
            out = self.add("Gather", [shp, self.i64([ax], "ax")], axis=0)
        else:  # composite: try d == sym * k for a known symbol
            out = None
            for sym, _, _ in self.sym_sources.values():
                try:
                    k = d // sym
                    k = int(k)
                except Exception:  # noqa: BLE001 - not divisible/symbolic
                    continue
                if sym * k == d:
                    out = self.add("Mul",
                                   [self._dyn_dim(sym), self.i64([k], "k")])
                    break
            if out is None:
                raise UnsupportedOnnxOp(
                    f"dynamic dimension expression '{d}' (supported: a "
                    f"traced symbol or symbol*constant)")
        self._dyn_cache[key] = out
        return out

    def shape_vec(self, dims, hint="shape"):
        """An int64[N] shape value: constant when every dim is static,
        else Concat of constant runs and runtime symbolic dims."""
        dims = list(dims)
        if all(isinstance(d, (int, np.integer)) for d in dims):
            return self.i64([int(d) for d in dims], hint)
        key = tuple(str(d) for d in dims)
        if key in self._shape_vec_cache:
            return self._shape_vec_cache[key]
        parts, run = [], []
        for d in dims:
            if isinstance(d, (int, np.integer)):
                run.append(int(d))
                continue
            if run:
                parts.append(self.i64(run, hint))
                run = []
            parts.append(self._dyn_dim(d))
        if run:
            parts.append(self.i64(run, hint))
        out = self.add("Concat", parts, axis=0)
        self._shape_vec_cache[key] = out
        return out


def convert_jaxpr(closed, input_names, builder=None):
    """Walk a ClosedJaxpr, emitting ONNX nodes; returns (builder,
    output_names)."""
    g = builder or GraphBuilder()
    env = {}

    jaxpr = closed.jaxpr

    def read(atom):
        from jax._src import core as jcore

        if isinstance(atom, jcore.Literal):
            return g.const(atom.val, "lit")
        return env[atom]

    for var, val in zip(jaxpr.constvars, closed.consts):
        env[var] = g.const(val, "w")
    for var, name in zip(jaxpr.invars, input_names):
        env[var] = name

    _emit_eqns(g, env, jaxpr.eqns, read)

    outs = []
    for ov in jaxpr.outvars:
        nm = read(ov)
        outs.append(nm)
    return g, outs


# --- emitters --------------------------------------------------------------

_SIMPLE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow",
    "neg": "Neg", "sign": "Sign", "floor": "Floor", "ceil": "Ceil",
    "round": "Round", "abs": "Abs", "exp": "Exp", "log": "Log",
    "tanh": "Tanh", "logistic": "Sigmoid", "erf": "Erf", "sqrt": "Sqrt",
    "sin": "Sin", "cos": "Cos", "tan": "Tan", "asin": "Asin",
    "acos": "Acos", "atan": "Atan", "sinh": "Sinh", "cosh": "Cosh",
    "asinh": "Asinh", "acosh": "Acosh", "atanh": "Atanh",
    "and": "And", "or": "Or", "xor": "Xor", "not": "Not",
    "eq": "Equal", "lt": "Less", "le": "LessOrEqual",
    "gt": "Greater", "ge": "GreaterOrEqual",
    "stop_gradient": "Identity", "copy": "Identity",
    "add_any": "Add",
}


def _static_ints(vals, what):
    """Require concrete ints (e.g. slice bounds): symbolic dims here must
    fail as UnsupportedOnnxOp naming the op, not a raw jax shape error."""
    out = []
    for v in vals:
        if not isinstance(v, (int, np.integer)):
            raise UnsupportedOnnxOp(
                f"{what} with a dynamic-dimension value ({v})")
        out.append(int(v))
    return out


def _scalar_like(g, eqn_invar, value):
    dt = _widen(eqn_invar.aval.dtype)
    return g.const(np.asarray(value, dt), "s")


def _ematch(name):
    def deco(fn):
        _EMITTERS[name] = fn
        return fn
    return deco


_EMITTERS = {}

for _jax_name, _onnx_name in _SIMPLE.items():
    def _mk(op):
        def _f(g, ins, eqn):
            return g.add(op, ins)
        return _f
    _EMITTERS[_jax_name] = _mk(_onnx_name)


@_ematch("ne")
def _ne(g, ins, eqn):
    return g.add("Not", [g.add("Equal", ins)])


@_ematch("rsqrt")
def _rsqrt(g, ins, eqn):
    return g.add("Reciprocal", [g.add("Sqrt", ins)])


@_ematch("log1p")
def _log1p(g, ins, eqn):
    one = _scalar_like(g, eqn.invars[0], 1)
    return g.add("Log", [g.add("Add", [ins[0], one])])


@_ematch("expm1")
def _expm1(g, ins, eqn):
    one = _scalar_like(g, eqn.invars[0], 1)
    return g.add("Sub", [g.add("Exp", ins), one])


@_ematch("rem")
def _rem(g, ins, eqn):
    # jax lax.rem keeps the dividend's sign (C fmod); ONNX Mod needs
    # fmod=1 for that (fmod=0 is integer-only, divisor-signed)
    return g.add("Mod", ins, fmod=1)


@_ematch("erfc")
def _erfc(g, ins, eqn):
    one = _scalar_like(g, eqn.invars[0], 1)
    return g.add("Sub", [one, g.add("Erf", ins)])


@_ematch("cbrt")
def _cbrt(g, ins, eqn):
    third = _scalar_like(g, eqn.invars[0], 1.0 / 3.0)
    return g.add("Pow", [ins[0], third])


@_ematch("integer_pow")
def _integer_pow(g, ins, eqn):
    y = _scalar_like(g, eqn.invars[0], eqn.params["y"])
    return g.add("Pow", [ins[0], y])


@_ematch("clamp")
def _clamp(g, ins, eqn):
    # jax: clamp(min, operand, max); min/max may be broadcast tensors, so
    # lower as elementwise Max(Min(x, hi), lo) rather than ONNX Clip
    lo, x, hi = ins
    return g.add("Max", [g.add("Min", [x, hi]), lo])


@_ematch("select_n")
def _select_n(g, ins, eqn):
    if len(ins) != 3:
        raise UnsupportedOnnxOp(f"select_n with {len(ins) - 1} cases")
    pred, case_f, case_t = ins
    return g.add("Where", [pred, case_t, case_f])


@_ematch("convert_element_type")
def _convert(g, ins, eqn):
    dt = _widen(eqn.params["new_dtype"])
    return g.add("Cast", ins, to=int(proto.NP_TO_ONNX[dt]))


@_ematch("reshape")
def _reshape(g, ins, eqn):
    if eqn.params.get("dimensions") is not None:
        perm = list(eqn.params["dimensions"])
        ins = [g.add("Transpose", ins, perm=perm)]
    return g.add("Reshape", [ins[0], g.shape_vec(eqn.params["new_sizes"])])


@_ematch("squeeze")
def _squeeze(g, ins, eqn):
    return g.add("Reshape", [ins[0], g.shape_vec(eqn.outvars[0].aval.shape)])


@_ematch("expand_dims")
def _expand_dims(g, ins, eqn):
    return g.add("Reshape", [ins[0], g.shape_vec(eqn.outvars[0].aval.shape)])


@_ematch("transpose")
def _transpose(g, ins, eqn):
    return g.add("Transpose", ins, perm=list(eqn.params["permutation"]))


@_ematch("broadcast_in_dim")
def _broadcast(g, ins, eqn):
    shape = list(eqn.params["shape"])
    bdims = list(eqn.params["broadcast_dimensions"])
    in_shape = eqn.invars[0].aval.shape
    mid = [1] * len(shape)
    for i, d in enumerate(bdims):
        mid[d] = in_shape[i]
    x = ins[0]
    if list(in_shape) != mid:
        x = g.add("Reshape", [x, g.shape_vec(mid)])
    if mid != shape:
        x = g.add("Expand", [x, g.shape_vec(shape)])
    elif x == ins[0]:
        x = g.add("Identity", [x])
    return x


@_ematch("concatenate")
def _concat(g, ins, eqn):
    return g.add("Concat", ins, axis=int(eqn.params["dimension"]))


@_ematch("slice")
def _slice(g, ins, eqn):
    starts = _static_ints(eqn.params["start_indices"], "slice starts")
    ends = _static_ints(eqn.params["limit_indices"], "slice limits")
    steps = _static_ints(eqn.params["strides"] or [1] * len(starts),
                         "slice strides")
    axes = list(range(len(starts)))
    return g.add("Slice", [ins[0], g.i64(starts), g.i64(ends),
                           g.i64(axes), g.i64(steps)])


@_ematch("rev")
def _rev(g, ins, eqn):
    dims = list(eqn.params["dimensions"])
    return g.add("Slice", [ins[0], g.i64([-1] * len(dims)),
                           g.i64([INT64_MIN] * len(dims)),
                           g.i64(dims), g.i64([-1] * len(dims))])


@_ematch("dynamic_slice")
def _dynamic_slice(g, ins, eqn):
    # runtime starts: Cast each scalar index to int64, Unsqueeze, Concat.
    # NOTE jax clamps out-of-range starts; ONNX Slice clamps ends only —
    # exported graphs must keep starts in range (true for the layer zoo).
    operand, idx = ins[0], ins[1:]
    sizes = _static_ints(eqn.params["slice_sizes"], "dynamic_slice sizes")
    parts = [g.add("Reshape",
                   [g.add("Cast", [i], to=int(proto.NP_TO_ONNX[np.dtype(np.int64)])),
                    g.i64([1])]) for i in idx]
    starts = g.add("Concat", parts, axis=0)
    ends = g.add("Add", [starts, g.i64(sizes)])
    axes = g.i64(list(range(len(sizes))))
    return g.add("Slice", [operand, starts, ends, axes])


@_ematch("pad")
def _pad(g, ins, eqn):
    cfg = eqn.params["padding_config"]
    if any(i != 0 for _, _, i in cfg):
        raise UnsupportedOnnxOp("interior (dilation) padding")
    los = [lo for lo, _, _ in cfg]
    his = [hi for _, hi, _ in cfg]
    x = ins[0]
    if any(v > 0 for v in los + his):
        pads = [max(v, 0) for v in los] + [max(v, 0) for v in his]
        x = g.add("Pad", [x, g.i64(pads), ins[1]], mode="constant")
    if any(v < 0 for v in los + his):  # negative padding == crop
        starts = [-min(v, 0) for v in los]
        shape = _static_ints(eqn.outvars[0].aval.shape,
                             "negative pad (crop) on a dynamic dim")
        ends = [s + e for s, e in zip(starts, shape)]
        x = g.add("Slice", [x, g.i64(starts), g.i64(ends),
                            g.i64(list(range(len(starts))))])
    return x


@_ematch("iota")
def _iota(g, ins, eqn):
    p = eqn.params
    dt = _widen(p["dtype"])
    shape, dim = list(p["shape"]), int(p["dimension"])
    view = [1] * len(shape)
    view[dim] = shape[dim]
    # store only the 1-D arange; Expand at run time (a broadcasted (S,S)
    # causal-mask iota would otherwise bake O(S^2) bytes into the file)
    if not isinstance(shape[dim], (int, np.integer)):
        raise UnsupportedOnnxOp(f"iota over a dynamic dimension ({shape})")
    rng = g.const(np.arange(shape[dim], dtype=dt).reshape(view), "iota")
    if view == shape:
        return g.add("Identity", [rng])
    return g.add("Expand", [rng, g.shape_vec(shape)])


@_ematch("gather")
def _gather(g, ins, eqn):
    dn = eqn.params["dimension_numbers"]
    sizes = tuple(eqn.params["slice_sizes"])
    op_shape = tuple(eqn.invars[0].aval.shape)
    if (len(dn.start_index_map) == 1 and dn.collapsed_slice_dims
            == dn.start_index_map and not getattr(dn, "operand_batching_dims",
                                                  ())):
        a = dn.start_index_map[0]
        want = op_shape[:a] + (1,) + op_shape[a + 1:]
        if sizes == want:
            idx_shape = tuple(eqn.invars[1].aval.shape)[:-1]
            out_shape = tuple(eqn.outvars[0].aval.shape)
            expect = op_shape[:a] + idx_shape + op_shape[a + 1:]
            if expect != out_shape:  # jnp.take with different offset layout
                raise UnsupportedOnnxOp(
                    f"gather layout {dn} (out {out_shape} != {expect})")
            idx = g.add("Reshape", [ins[1], g.shape_vec(idx_shape or [1])])
            out = g.add("Gather", [ins[0], idx], axis=int(a))
            if not idx_shape:  # scalar take: drop the kept unit dim
                out = g.add("Reshape", [out, g.shape_vec(out_shape)])
            return out
    raise UnsupportedOnnxOp(f"general gather {dn} sizes={sizes}")


@_ematch("dot_general")
def _dot_general(g, ins, eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    l_aval, r_aval = eqn.invars[0].aval, eqn.invars[1].aval
    ls, rs = tuple(l_aval.shape), tuple(r_aval.shape)
    lhs, rhs = ins
    out_shape = tuple(eqn.outvars[0].aval.shape)

    # fast path: plain matmul semantics (no batch, contract last x first,
    # rhs at most rank 2 — higher-rank rhs needs the general lowering)
    if (not lb and len(lc) == 1 and lc[0] == len(ls) - 1
            and rc == (0,) and len(rs) <= 2):
        out = g.add("MatMul", [lhs, rhs])
    else:
        lfree = [d for d in range(len(ls)) if d not in lc and d not in lb]
        rfree = [d for d in range(len(rs)) if d not in rc and d not in rb]

        def prod(dims):
            out = 1
            for d in dims:
                out = out * d  # symbolic dims overload *
            return out

        B = prod(ls[d] for d in lb)
        M = prod(ls[d] for d in lfree)
        K = prod(ls[d] for d in lc)
        N = prod(rs[d] for d in rfree)
        l2 = g.add("Transpose", [lhs], perm=list(lb) + lfree + list(lc))
        l2 = g.add("Reshape", [l2, g.shape_vec([B, M, K])])
        r2 = g.add("Transpose", [rhs], perm=list(rb) + list(rc) + rfree)
        r2 = g.add("Reshape", [r2, g.shape_vec([B, K, N])])
        mm = g.add("MatMul", [l2, r2])
        out = g.add("Reshape", [mm, g.shape_vec(out_shape)])

    out_dt = _widen(eqn.outvars[0].aval.dtype)
    if out_dt != _widen(l_aval.dtype):
        out = g.add("Cast", [out], to=int(proto.NP_TO_ONNX[out_dt]))
    return out


@_ematch("conv_general_dilated")
def _conv(g, ins, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    if any(d != 1 for d in p["lhs_dilation"]):
        raise UnsupportedOnnxOp("transposed convolution (lhs_dilation)")
    if p.get("batch_group_count", 1) != 1:
        raise UnsupportedOnnxOp("batch_group_count != 1")
    lhs_spec, rhs_spec, out_spec = dn
    x = g.add("Transpose", [ins[0]], perm=list(lhs_spec))   # -> NC(spatial)
    w = g.add("Transpose", [ins[1]], perm=list(rhs_spec))   # -> OI(spatial)
    pads = [lo for lo, _ in p["padding"]] + [hi for _, hi in p["padding"]]
    y = g.add("Conv", [x, w],
              strides=list(p["window_strides"]),
              pads=pads,
              dilations=list(p["rhs_dilation"]),
              group=int(p["feature_group_count"]))
    # out_spec[i] = jax position of canonical dim i; the Conv result is
    # canonical NC(spatial), so jax dim j = canonical dim inv[j]
    inv = [0] * len(out_spec)
    for i, d in enumerate(out_spec):
        inv[d] = i
    return g.add("Transpose", [y], perm=inv)


def _pool_layout(eqn):
    p = eqn.params
    win = list(p["window_dimensions"])
    strides = list(p["window_strides"])
    padding = list(p["padding"])
    if any(d != 1 for d in list(p.get("base_dilation", [])) or [1]):
        raise UnsupportedOnnxOp("pool base_dilation")
    if any(d != 1 for d in list(p.get("window_dilation", [])) or [1]):
        raise UnsupportedOnnxOp("pool window_dilation")
    spatial = [i for i, w in enumerate(win) if w != 1 or strides[i] != 1
               or padding[i] != (0, 0)]
    passive = [i for i in range(len(win)) if i not in spatial]
    if len(passive) < 2:
        raise UnsupportedOnnxOp(f"pool window {win} has no N/C dims")
    # N and C = the first two passive dims in order; everything windowed
    # (plus remaining passive dims, windows of 1) is spatial
    spatial = [i for i in range(len(win)) if i not in passive[:2]]
    perm = passive[:2] + spatial
    return perm, [win[i] for i in spatial], [strides[i] for i in spatial], \
        ([padding[i][0] for i in spatial] + [padding[i][1] for i in spatial])


def _emit_pool(g, ins, eqn, op, **extra):
    perm, kernel, strides, pads = _pool_layout(eqn)
    x = g.add("Transpose", [ins[0]], perm=perm)
    y = g.add(op, [x], kernel_shape=kernel, strides=strides, pads=pads,
              **extra)
    inv = [0] * len(perm)
    for i, d in enumerate(perm):
        inv[d] = i
    return g.add("Transpose", [y], perm=inv)


@_ematch("reduce_window_max")
def _maxpool(g, ins, eqn):
    return _emit_pool(g, ins, eqn, "MaxPool")


@_ematch("reduce_window_sum")
def _sumpool(g, ins, eqn):
    perm, kernel, _, _ = _pool_layout(eqn)
    avg = _emit_pool(g, ins, eqn, "AveragePool", count_include_pad=1)
    n = _scalar_like(g, eqn.invars[0], float(np.prod(kernel)))
    return g.add("Mul", [avg, n])


def _reduce(onnx_op, axes_as_input):
    def _f(g, ins, eqn):
        axes = [int(a) for a in eqn.params["axes"]]
        if axes_as_input:  # ReduceSum carries axes as an input in opset 13
            return g.add(onnx_op, [ins[0], g.i64(axes)], keepdims=0)
        return g.add(onnx_op, ins, axes=axes, keepdims=0)
    return _f


_EMITTERS["reduce_sum"] = _reduce("ReduceSum", True)
_EMITTERS["reduce_max"] = _reduce("ReduceMax", False)
_EMITTERS["reduce_min"] = _reduce("ReduceMin", False)
_EMITTERS["reduce_prod"] = _reduce("ReduceProd", False)


def _reduce_bool(onnx_op):
    def _f(g, ins, eqn):
        axes = [int(a) for a in eqn.params["axes"]]
        f = g.add("Cast", ins, to=proto.FLOAT)
        if onnx_op == "ReduceMin":  # all()
            r = g.add("ReduceMin", [f], axes=axes, keepdims=0)
        else:                        # any()
            r = g.add("ReduceMax", [f], axes=axes, keepdims=0)
        half = g.const(np.float32(0.5))
        return g.add("Greater", [r, half])
    return _f


_EMITTERS["reduce_and"] = _reduce_bool("ReduceMin")
_EMITTERS["reduce_or"] = _reduce_bool("ReduceMax")


def _arg_reduce(onnx_op):
    def _f(g, ins, eqn):
        axes = list(eqn.params["axes"])
        if len(axes) != 1:
            raise UnsupportedOnnxOp(f"{onnx_op} over {axes}")
        out = g.add(onnx_op, ins, axis=int(axes[0]), keepdims=0)
        dt = _widen(eqn.params["index_dtype"])
        if dt != np.dtype(np.int64):
            out = g.add("Cast", [out], to=int(proto.NP_TO_ONNX[dt]))
        return out
    return _f


_EMITTERS["argmax"] = _arg_reduce("ArgMax")
_EMITTERS["argmin"] = _arg_reduce("ArgMin")


@_ematch("cumsum")
def _cumsum(g, ins, eqn):
    axis = g.const(np.asarray(eqn.params["axis"], np.int64))
    return g.add("CumSum", [ins[0], axis],
                 reverse=int(bool(eqn.params.get("reverse", False))))


@_ematch("square")
def _square(g, ins, eqn):
    return g.add("Mul", [ins[0], ins[0]])


@_ematch("is_finite")
def _is_finite(g, ins, eqn):
    nan = g.add("IsNaN", ins)
    inf = g.add("IsInf", ins)
    return g.add("Not", [g.add("Or", [nan, inf])])


# --- call-like primitives: inline the inner jaxpr --------------------------


def _inline(g, env, eqn, closed, read):
    inner = closed.jaxpr
    sub_env = {}
    for var, val in zip(inner.constvars, closed.consts):
        sub_env[var] = g.const(val, "w")
    for var, outer in zip(inner.invars, eqn.invars):
        sub_env[var] = read(outer)

    def sub_read(atom):
        from jax._src import core as jcore

        if isinstance(atom, jcore.Literal):
            return g.const(atom.val, "lit")
        return sub_env[atom]

    _emit_eqns(g, sub_env, inner.eqns, sub_read)
    return [sub_read(ov) for ov in inner.outvars]


def _closed_of(eqn):
    from jax._src import core as jcore

    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        cj = eqn.params.get(key)
        if cj is None:
            continue
        if isinstance(cj, jcore.ClosedJaxpr):
            return cj
        return jcore.ClosedJaxpr(cj, ())
    raise UnsupportedOnnxOp(f"call primitive without jaxpr: {eqn}")


_CALL_PRIMS = ("jit", "pjit", "closed_call", "core_call", "xla_call", "remat2",
               "checkpoint", "custom_jvp_call", "custom_vjp_call",
               "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr")


def _emit_eqns(g, env, eqns, read):
    from jax._src import core as jcore

    for eqn in eqns:
        prim = eqn.primitive.name
        if prim in _CALL_PRIMS or (prim.startswith("custom_")
                                   and "call" in prim):
            outs = _inline(g, env, eqn, _closed_of(eqn), read)
            for var, nm in zip(eqn.outvars, outs):
                env[var] = nm
            continue
        ins = [read(v) for v in eqn.invars]
        emit = _EMITTERS.get(prim)
        if emit is None:
            raise UnsupportedOnnxOp(
                f"primitive '{prim}' has no ONNX mapping (eqn: {eqn})")
        outs = emit(g, ins, eqn)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        for var, nm in zip(eqn.outvars, outs):
            if isinstance(var, jcore.DropVar):
                continue
            env[var] = nm
