"""Minimal ONNX protobuf wire-format codec (no external deps).

The ONNX serialization format is protobuf; this module hand-encodes the
small subset of onnx.proto needed for inference graphs (ModelProto /
GraphProto / NodeProto / TensorProto / ValueInfoProto / AttributeProto)
using the public field numbers from the ONNX spec, and provides a generic
decoder for round-trip validation and the numpy runtime.

Why hand-rolled: this image ships protoc 3.21 but protobuf-python 6.x,
whose generated-code version check rejects 3.21 gencode — and the `onnx`
package itself is absent.  The wire format (varint / length-delimited)
is trivial and stable, so encoding it directly is the dependency-free
path.  Reference behavior target: python/paddle/onnx/export.py (which
delegates to paddle2onnx); the artifact layout (`<path>.onnx` ModelProto)
matches what that produces.
"""
from __future__ import annotations

import struct

import numpy as np

# --- ONNX enums (public spec values) --------------------------------------

# TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

NP_TO_ONNX = {
    np.dtype(np.float32): FLOAT, np.dtype(np.float64): DOUBLE,
    np.dtype(np.float16): FLOAT16, np.dtype(np.int8): INT8,
    np.dtype(np.uint8): UINT8, np.dtype(np.int16): INT16,
    np.dtype(np.uint16): UINT16, np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64, np.dtype(np.uint32): UINT32,
    np.dtype(np.uint64): UINT64, np.dtype(np.bool_): BOOL,
}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR = 1, 2, 3, 4
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8

# --- wire-level encoding ---------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:  # proto int64 negative: 10-byte two's-complement varint
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def vint(field: int, value: int) -> bytes:
    """varint-typed field (int32/int64/enum/bool)."""
    return _tag(field, 0) + _varint(int(value))


def ld(field: int, payload: bytes) -> bytes:
    """length-delimited field (string/bytes/sub-message/packed)."""
    return _tag(field, 2) + _varint(len(payload)) + payload


def s(field: int, text) -> bytes:
    return ld(field, text if isinstance(text, bytes) else text.encode())


def f32(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def packed_i64(field: int, values) -> bytes:
    return ld(field, b"".join(_varint(int(v)) for v in values))


def packed_f32(field: int, values) -> bytes:
    return ld(field, struct.pack(f"<{len(values)}f", *values))


# --- message builders (field numbers from the public onnx.proto) -----------


def tensor(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, raw_data=9, name=8."""
    arr = np.ascontiguousarray(arr)
    dt = NP_TO_ONNX[arr.dtype]
    return (packed_i64(1, arr.shape)
            + vint(2, dt)
            + s(8, name)
            + ld(9, arr.tobytes()))


def _attr(name: str, value) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    strings=9, type=20."""
    body = s(1, name)
    if isinstance(value, bool):
        return body + vint(3, int(value)) + vint(20, A_INT)
    if isinstance(value, int):
        return body + vint(3, value) + vint(20, A_INT)
    if isinstance(value, float):
        return body + f32(2, value) + vint(20, A_FLOAT)
    if isinstance(value, (str, bytes)):
        return body + s(4, value) + vint(20, A_STRING)
    if isinstance(value, np.ndarray):
        return body + ld(5, tensor(name, value)) + vint(20, A_TENSOR)
    if isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, np.integer)) for v in value):
            return (body + b"".join(vint(8, int(v)) for v in value)
                    + vint(20, A_INTS))
        if all(isinstance(v, (float, np.floating)) for v in value):
            return (body + b"".join(f32(7, float(v)) for v in value)
                    + vint(20, A_FLOATS))
        if all(isinstance(v, (str, bytes)) for v in value):
            return (body + b"".join(s(9, v) for v in value)
                    + vint(20, A_STRINGS))
    raise TypeError(f"unsupported attribute {name}={value!r}")


def node(op_type: str, inputs, outputs, name: str = "", **attrs) -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    return (b"".join(s(1, i) for i in inputs)
            + b"".join(s(2, o) for o in outputs)
            + s(3, name or (op_type + "_" + (outputs[0] if outputs else "")))
            + s(4, op_type)
            + b"".join(ld(5, _attr(k, v)) for k, v in attrs.items()))


def value_info(name: str, dtype: np.dtype, shape) -> bytes:
    """ValueInfoProto{name=1, type=2} / TypeProto{tensor_type=1} /
    TypeProto.Tensor{elem_type=1, shape=2} / TensorShapeProto{dim=1} /
    Dimension{dim_value=1, dim_param=2}."""
    dims = b""
    for d in shape:
        if isinstance(d, int) and d >= 0:
            dims += ld(1, vint(1, d))
        else:  # symbolic / unknown
            dims += ld(1, s(2, str(d)))
    tensor_type = vint(1, NP_TO_ONNX[np.dtype(dtype)]) + ld(2, dims)
    return s(1, name) + ld(2, ld(1, tensor_type))


def graph(nodes, name, inputs, outputs, initializers) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    return (b"".join(ld(1, n) for n in nodes)
            + s(2, name)
            + b"".join(ld(5, t) for t in initializers)
            + b"".join(ld(11, vi) for vi in inputs)
            + b"".join(ld(12, vi) for vi in outputs))


def model(graph_bytes: bytes, opset_version: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8.
    OperatorSetIdProto: domain=1 (default ''), version=2."""
    return (vint(1, 8)  # IR version 8 (opset 13-17 era)
            + s(2, producer)
            + ld(7, graph_bytes)
            + ld(8, vint(2, opset_version)))


# --- generic decoder -------------------------------------------------------


def parse(data: bytes):
    """Decode one protobuf message into {field_no: [values]} where a value
    is an int (wire 0), a 4/8-byte struct (wire 5/1, returned as raw
    bytes), or bytes (wire 2 — caller re-parses sub-messages)."""
    fields = {}
    i, n = 0, len(data)
    while i < n:
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(data, i)
        elif wire == 2:
            ln, i = _read_varint(data, i)
            v = data[i:i + ln]
            i += ln
        elif wire == 5:
            v = data[i:i + 4]
            i += 4
        elif wire == 1:
            v = data[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(v)
    return fields


def _read_varint(data: bytes, i: int):
    shift = result = 0
    while True:
        b = data[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def parse_packed_i64(payload: bytes):
    out, i = [], 0
    while i < len(payload):
        v, i = _read_varint(payload, i)
        if v >= 1 << 63:
            v -= 1 << 64
        out.append(v)
    return out


def signed(v: int) -> int:
    """Interpret a decoded varint as int64."""
    return v - (1 << 64) if v >= 1 << 63 else v
