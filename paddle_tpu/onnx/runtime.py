"""Numpy evaluator for the ONNX op subset the exporter emits.

Exists so exported artifacts can be validated end-to-end in this image
(which has no `onnx`/`onnxruntime`): tests export a Layer, re-load the
.onnx bytes through the generic protobuf decoder, execute the graph in
numpy, and compare against the Layer's own forward.  It is a validation
runtime, not a serving engine — the serving path is StableHLO via
paddle_tpu.inference (reference analog: paddle2onnx consumers vs
AnalysisPredictor, analysis_predictor.cc:306).
"""
from __future__ import annotations

import struct

import numpy as np

from . import proto


def _u(b):
    return b.decode()


class _Msg:
    """Typed view over proto.parse output."""

    def __init__(self, data: bytes):
        self.f = proto.parse(data)

    def ints(self, n):
        return [proto.signed(v) for v in self.f.get(n, [])]

    def int(self, n, default=0):
        v = self.f.get(n)
        return proto.signed(v[0]) if v else default

    def strs(self, n):
        return [_u(v) for v in self.f.get(n, [])]

    def str_(self, n, default=""):
        v = self.f.get(n)
        return _u(v[0]) if v else default

    def subs(self, n):
        return [_Msg(v) for v in self.f.get(n, [])]

    def sub(self, n):
        v = self.f.get(n)
        return _Msg(v[0]) if v else None

    def bytes_(self, n):
        v = self.f.get(n)
        return v[0] if v else b""

    def float_(self, n, default=0.0):
        v = self.f.get(n)
        return struct.unpack("<f", v[0])[0] if v else default


def _tensor_to_np(t: _Msg) -> np.ndarray:
    if 1 not in t.f:
        dims = []
    elif isinstance(t.f[1][0], int):  # dims as unpacked wire-0 varints
        dims = [proto.signed(v) for v in t.f[1]]
    else:                             # packed (what proto.tensor emits)
        dims = proto.parse_packed_i64(t.f[1][0])
    dt = proto.ONNX_TO_NP[t.int(2)]
    raw = t.bytes_(9)
    if raw:
        return np.frombuffer(raw, dt).reshape(dims).copy()
    return np.zeros(dims, dt)


class _Attr:
    def __init__(self, m: _Msg):
        self.name = m.str_(1)
        self.type = m.int(20)
        self.m = m

    @property
    def value(self):
        t = self.type
        if t == proto.A_INT:
            return self.m.int(3)
        if t == proto.A_FLOAT:
            return self.m.float_(2)
        if t == proto.A_STRING:
            return self.m.str_(4)
        if t == proto.A_INTS:
            return self.m.ints(8)
        if t == proto.A_FLOATS:
            return [struct.unpack("<f", v)[0] for v in self.m.f.get(7, [])]
        if t == proto.A_TENSOR:
            return _tensor_to_np(self.m.sub(5))
        raise ValueError(f"attr type {t}")


class Node:
    def __init__(self, m: _Msg):
        self.inputs = m.strs(1)
        self.outputs = m.strs(2)
        self.op_type = m.str_(4)
        self.attrs = {a.name: a.value
                      for a in (_Attr(x) for x in m.subs(5))}


class ONNXModel:
    """Parse + execute a ModelProto produced by paddle_tpu.onnx.export."""

    def __init__(self, path_or_bytes):
        if isinstance(path_or_bytes, str):
            with open(path_or_bytes, "rb") as f:
                path_or_bytes = f.read()
        model = _Msg(path_or_bytes)
        self.ir_version = model.int(1)
        self.opset = (model.subs(8)[0].int(2)) if model.subs(8) else 0
        g = model.sub(7)
        self.graph_name = g.str_(2)
        self.nodes = [Node(n) for n in g.subs(1)]
        self.initializers = {t.str_(8): _tensor_to_np(t) for t in g.subs(5)}
        self.input_names = [vi.str_(1) for vi in g.subs(11)]
        self.output_names = [vi.str_(1) for vi in g.subs(12)]

    def run(self, feeds):
        if isinstance(feeds, (list, tuple)):
            feeds = dict(zip(self.input_names, feeds))
        env = dict(self.initializers)
        for k, v in feeds.items():
            env[k] = np.asarray(v)
        for node in self.nodes:
            fn = _OPS.get(node.op_type)
            if fn is None:
                raise NotImplementedError(f"runtime op {node.op_type}")
            args = [env[i] if i else None for i in node.inputs]
            out = fn(node, *args)
            if not isinstance(out, tuple):
                out = (out,)
            for name, val in zip(node.outputs, out):
                env[name] = val
        return [env[o] for o in self.output_names]


# --- op table --------------------------------------------------------------

_OPS = {}


def _op(name):
    def deco(fn):
        _OPS[name] = fn
        return fn
    return deco


def _binop(name, fn):
    _OPS[name] = lambda n, a, b: fn(a, b)


def _unop(name, fn):
    _OPS[name] = lambda n, a: fn(a)


_binop("Add", lambda a, b: a + b)
_binop("Sub", lambda a, b: a - b)
_binop("Mul", lambda a, b: a * b)
# integer Div truncates toward zero (ONNX spec + lax.div), not floor
_binop("Div", lambda a, b: a / b if a.dtype.kind == "f"
       else (np.sign(a) * np.sign(b) * (np.abs(a) // np.abs(b))).astype(a.dtype))
_binop("Pow", lambda a, b: np.power(a, b.astype(a.dtype)))
_binop("Mod", np.fmod)
_binop("Max", np.maximum)
_binop("Min", np.minimum)
_binop("And", np.logical_and)
_binop("Or", np.logical_or)
_binop("Xor", np.logical_xor)
_binop("Equal", lambda a, b: a == b)
_binop("Less", lambda a, b: a < b)
_binop("LessOrEqual", lambda a, b: a <= b)
_binop("Greater", lambda a, b: a > b)
_binop("GreaterOrEqual", lambda a, b: a >= b)
_binop("MatMul", lambda a, b: np.matmul(a, b))
_unop("Neg", np.negative)
_unop("Abs", np.abs)
_unop("Sign", np.sign)
_unop("Floor", np.floor)
_unop("Ceil", np.ceil)
_unop("Round", lambda a: np.round(a))
_unop("Exp", np.exp)
_unop("Log", np.log)
_unop("Sqrt", np.sqrt)
_unop("Reciprocal", lambda a: 1.0 / a)
_unop("Tanh", np.tanh)
_unop("Sigmoid", lambda a: 1.0 / (1.0 + np.exp(-a)))
_unop("Sin", np.sin)
_unop("Cos", np.cos)
_unop("Tan", np.tan)
_unop("Asin", np.arcsin)
_unop("Acos", np.arccos)
_unop("Atan", np.arctan)
_unop("Sinh", np.sinh)
_unop("Cosh", np.cosh)
_unop("Asinh", np.arcsinh)
_unop("Acosh", np.arccosh)
_unop("Atanh", np.arctanh)
_unop("Not", np.logical_not)
_unop("Identity", lambda a: a)
_unop("IsNaN", np.isnan)
_unop("IsInf", np.isinf)


@_op("Erf")
def _erf(n, a):
    # Abramowitz-Stegun 7.1.26 is too lossy for parity tests; use the
    # complementary construction via numpy's vectorized math.erf
    from math import erf
    return np.vectorize(erf, otypes=[a.dtype])(a)


@_op("Where")
def _where(n, c, x, y):
    return np.where(c, x, y)


@_op("Shape")
def _shape(n, a):
    return np.asarray(a.shape, np.int64)


@_op("Cast")
def _cast(n, a):
    return a.astype(proto.ONNX_TO_NP[n.attrs["to"]])


@_op("Reshape")
def _reshape(n, a, shape):
    shape = [int(s) for s in shape]
    return a.reshape(shape)


@_op("Transpose")
def _transpose(n, a):
    return np.transpose(a, n.attrs.get("perm"))


@_op("Expand")
def _expand(n, a, shape):
    return np.broadcast_to(a, [int(s) for s in shape]).copy()


@_op("Concat")
def _concat(n, *xs):
    return np.concatenate(xs, axis=n.attrs["axis"])


@_op("Gather")
def _gather(n, a, idx):
    return np.take(a, idx.astype(np.int64), axis=n.attrs.get("axis", 0))


@_op("Slice")
def _slice(n, data, starts, ends, axes=None, steps=None):
    starts = [int(v) for v in starts]
    ends = [int(v) for v in ends]
    axes = list(range(len(starts))) if axes is None else [int(v) for v in axes]
    steps = [1] * len(starts) if steps is None else [int(v) for v in steps]
    sl = [slice(None)] * data.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        if sp < 0 and en <= -(1 << 62):  # INT64_MIN sentinel: to the start
            en = None
        sl[ax] = slice(st, en, sp)
    return data[tuple(sl)].copy()


@_op("Pad")
def _pad(n, data, pads, value=None):
    k = data.ndim
    pads = [int(p) for p in pads]
    width = [(pads[i], pads[k + i]) for i in range(k)]
    cv = float(value) if value is not None and value.dtype.kind == "f" \
        else (int(value) if value is not None else 0)
    return np.pad(data, width, constant_values=cv)


def _reduce(np_fn):
    def f(n, a, axes_in=None):
        axes = n.attrs.get("axes")
        if axes_in is not None:
            axes = [int(v) for v in axes_in]
        axes = tuple(axes) if axes else None
        keep = bool(n.attrs.get("keepdims", 1))
        return np_fn(a, axis=axes, keepdims=keep)
    return f


_OPS["ReduceSum"] = _reduce(np.sum)
_OPS["ReduceMax"] = _reduce(np.max)
_OPS["ReduceMin"] = _reduce(np.min)
_OPS["ReduceProd"] = _reduce(np.prod)
_OPS["ReduceMean"] = _reduce(np.mean)


@_op("ArgMax")
def _argmax(n, a):
    out = np.argmax(a, axis=n.attrs["axis"])
    return out if n.attrs.get("keepdims", 1) == 0 \
        else np.expand_dims(out, n.attrs["axis"])


@_op("ArgMin")
def _argmin(n, a):
    out = np.argmin(a, axis=n.attrs["axis"])
    return out if n.attrs.get("keepdims", 1) == 0 \
        else np.expand_dims(out, n.attrs["axis"])


@_op("CumSum")
def _cumsum(n, a, axis):
    ax = int(np.asarray(axis).reshape(()))
    if n.attrs.get("reverse"):
        return np.flip(np.cumsum(np.flip(a, axis=ax), axis=ax), axis=ax)
    return np.cumsum(a, axis=ax)


def _pool_view(a, kernel, strides, pads):
    """(N, C, *spatial) -> windows (N, C, *out_spatial, *kernel)."""
    k = len(kernel)
    if any(p != 0 for p in pads):
        width = [(0, 0), (0, 0)] + [(pads[i], pads[k + i]) for i in range(k)]
        a = np.pad(a, width, constant_values=0)
    from numpy.lib.stride_tricks import sliding_window_view
    win = sliding_window_view(a, kernel, axis=tuple(range(2, 2 + k)))
    idx = (slice(None), slice(None)) + tuple(
        slice(None, None, s) for s in strides)
    return win[idx + (Ellipsis,)]


@_op("MaxPool")
def _maxpool(n, a):
    k = len(n.attrs["kernel_shape"])
    pads = n.attrs.get("pads", [0] * 2 * k)
    if any(p != 0 for p in pads):
        # pad with -inf so padding never wins the max
        width = [(0, 0), (0, 0)] + [(pads[i], pads[k + i]) for i in range(k)]
        a = np.pad(a, width, constant_values=-np.inf if a.dtype.kind == "f"
                   else np.iinfo(a.dtype).min)
        pads = [0] * 2 * k
    v = _pool_view(a, n.attrs["kernel_shape"],
                   n.attrs.get("strides", [1] * k), pads)
    return v.max(axis=tuple(range(-k, 0)))


@_op("AveragePool")
def _avgpool(n, a):
    k = len(n.attrs["kernel_shape"])
    v = _pool_view(a, n.attrs["kernel_shape"],
                   n.attrs.get("strides", [1] * k),
                   n.attrs.get("pads", [0] * 2 * k))
    # exporter always sets count_include_pad=1
    return v.mean(axis=tuple(range(-k, 0)))


@_op("Conv")
def _conv(n, x, w, b=None):
    strides = n.attrs.get("strides")
    dil = n.attrs.get("dilations")
    group = n.attrs.get("group", 1)
    k = w.ndim - 2
    strides = strides or [1] * k
    dil = dil or [1] * k
    pads = n.attrs.get("pads", [0] * 2 * k)
    if any(d != 1 for d in dil):  # dilate the kernel explicitly
        wd_shape = list(w.shape[:2]) + [
            (w.shape[2 + i] - 1) * dil[i] + 1 for i in range(k)]
        wd = np.zeros(wd_shape, w.dtype)
        wd[(slice(None), slice(None))
           + tuple(slice(None, None, dil[i]) for i in range(k))] = w
        w = wd
    width = [(0, 0), (0, 0)] + [(pads[i], pads[k + i]) for i in range(k)]
    x = np.pad(x, width)
    N, C = x.shape[:2]
    O, I = w.shape[:2]  # I = C // group
    from numpy.lib.stride_tricks import sliding_window_view
    win = sliding_window_view(x, w.shape[2:], axis=tuple(range(2, 2 + k)))
    win = win[(slice(None), slice(None))
              + tuple(slice(None, None, s) for s in strides) + (Ellipsis,)]
    # win: (N, C, *out, *kern); contract per group
    og = O // group
    outs = []
    for gi in range(group):
        wg = w[gi * og:(gi + 1) * og]          # (og, I, *kern)
        xg = win[:, gi * I:(gi + 1) * I]       # (N, I, *out, *kern)
        outs.append(np.einsum(
            xg, [0, 1] + list(range(2, 2 + k)) + list(range(10, 10 + k)),
            wg, [9, 1] + list(range(10, 10 + k)),
            [0, 9] + list(range(2, 2 + k))))
    y = np.concatenate(outs, axis=1)
    if b is not None:
        y = y + b.reshape((1, -1) + (1,) * k)
    return y.astype(x.dtype)
