"""Fused transformer-path ops.

Reference parity: paddle/fluid/operators/fused/ — multihead_matmul_op.cu
(BERT attention), skip_layernorm_op.cu (residual+LN), layer_norm_op.cu fused
kernels, softmax_with_cross_entropy_op.cu (fused loss), and
math/bert_encoder_functor.cu.  BASELINE.json additionally names
fused_attention / fused_feedforward / fused_multi_transformer as intent.

TPU-native: each fused op has an XLA composite implementation (XLA fuses the
elementwise pieces into the matmuls on its own) and, for the hot ones, a
Pallas TPU kernel (ops/pallas/) that takes over when FLAGS_use_pallas_kernels
is on AND the arrays live on a TPU backend.  Selection happens here.
"""
from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp

from ..framework.flags import flag
from ..tensor import Tensor, apply, unwrap


@functools.lru_cache(maxsize=1)
def _tpu_available() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _use_pallas() -> bool:
    return bool(flag("FLAGS_use_pallas_kernels")) and _tpu_available()


# ---------------------------------------------------------------------------
# fallback telemetry: an accidentally-XLA hot path must be VISIBLE
# ---------------------------------------------------------------------------
_warned_sites: set = set()


def fallback_counter():
    """The shared-registry `paddle_pallas_fallbacks_total{kernel,reason}`
    counter (zero-initialized lazily; rendered by /metrics)."""
    from ..utils.metrics import default_registry

    return default_registry().counter(
        "paddle_pallas_fallbacks_total",
        "fused-op calls that fell back to XLA while "
        "FLAGS_use_pallas_kernels was on, by kernel and reason",
        label=("kernel", "reason"))


def _note_fallback(kernel: str, reason: str):
    """Record one Pallas->XLA fallback: bump the shared-registry counter
    and warn ONCE per (kernel, reason) site.  Dispatch happens at trace
    time, so one recorded fallback means every step of that compiled
    graph runs the XLA path."""
    fallback_counter().inc((kernel, reason))
    site = (kernel, reason)
    if site not in _warned_sites:
        _warned_sites.add(site)
        warnings.warn(
            f"FLAGS_use_pallas_kernels is on but '{kernel}' fell back to "
            f"the XLA composite ({reason}); the hot path is NOT running "
            f"the Pallas kernel (see paddle_pallas_fallbacks_total in "
            f"/metrics)", RuntimeWarning, stacklevel=3)


def _fallback_reason(exc: Exception) -> str:
    if isinstance(exc, NotImplementedError):
        return "mask_shape" if "mask" in str(exc) else "shape"
    return type(exc).__name__


def _mesh_axes():
    """(mesh, batch_axes, tp_axis) for kernel shard_map composition:
    batch axes are the >1-sized data axes ('dp'/'fsdp'), tp is the
    >1-sized head/column axis under either naming scheme — the models'
    in-layer 'mp' pin or SpecLayout's 'tp'."""
    try:
        from ..distributed.mesh import get_mesh

        mesh = get_mesh()
    except Exception:  # noqa: BLE001 - no distributed state, solo jit
        return None, (), None
    if mesh is None:
        return None, (), None
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    batch = tuple(a for a in ("dp", "fsdp") if sizes.get(a, 1) > 1)
    tp = next((a for a in ("mp", "tp") if sizes.get(a, 1) > 1), None)
    if not batch and tp is None:
        return None, (), None
    return mesh, batch, tp


def _axes_size(mesh, axes) -> int:
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n = 1
    for a in axes:
        n *= sizes.get(a, 1)
    return n


def _rows_divisible(dim: int, mesh, axes) -> bool:
    return dim % _axes_size(mesh, axes) == 0


# ---------------------------------------------------------------------------
# layer norm (fused scale+shift; Pallas row kernel on TPU)
# ---------------------------------------------------------------------------
def layer_norm(x, weight, bias, epsilon=1e-5):
    if _use_pallas():
        from .pallas import layer_norm as pln

        try:
            return apply(lambda v, w, b: pln.layer_norm(v, w, b, epsilon),
                         x, weight, bias)
        except Exception as e:  # noqa: BLE001 - counted, then composite
            _note_fallback("layer_norm", _fallback_reason(e))

    def f(v, w, b):
        mean = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(v - mean), axis=-1, keepdims=True)
        return (v - mean) * jax.lax.rsqrt(var + epsilon) * w + b

    return apply(f, x, weight, bias)


def skip_layer_norm(x, residual, weight, bias, epsilon=1e-5):
    """residual-add + LN in one op (skip_layernorm_op.cu analog)."""
    def f(v, r, w, b):
        h = v + r
        mean = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(h - mean), axis=-1, keepdims=True)
        return (h - mean) * jax.lax.rsqrt(var + epsilon) * w + b
    return apply(f, x, residual, weight, bias)


# ---------------------------------------------------------------------------
# softmax cross entropy (fused, numerically stable)
# ---------------------------------------------------------------------------
def softmax_cross_entropy(logits, label, ignore_index=-100):
    if _use_pallas():
        from .pallas import softmax_xent as sx

        try:
            mesh, batch, _ = _mesh_axes()

            def pf(z, l):
                if mesh is not None and batch and z.ndim >= 2 \
                        and _rows_divisible(z.shape[0], mesh, batch):
                    from jax.experimental.shard_map import shard_map
                    from jax.sharding import PartitionSpec as P

                    bspec = batch if len(batch) > 1 else batch[0]
                    li = l if l.ndim == z.ndim - 1 else jnp.squeeze(l, -1)
                    body = functools.partial(sx.softmax_xent,
                                             ignore_index=ignore_index)
                    return shard_map(
                        body, mesh=mesh,
                        in_specs=(P(bspec, *([None] * (z.ndim - 1))),
                                  P(bspec, *([None] * (li.ndim - 1)))),
                        out_specs=P(bspec, *([None] * (z.ndim - 2))),
                        check_rep=False)(z, li)
                return sx.softmax_xent(z, l, ignore_index=ignore_index)

            return apply(pf, logits, label)
        except Exception as e:  # noqa: BLE001 - counted, then composite
            _note_fallback("softmax_xent", _fallback_reason(e))

    def f(z, l):
        li = l.astype(jnp.int32)
        if li.ndim == z.ndim:
            li = jnp.squeeze(li, -1)
        m = jnp.max(z, axis=-1, keepdims=True)
        shifted = z - jax.lax.stop_gradient(m)
        lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1))
        picked = jnp.take_along_axis(shifted, li[..., None], axis=-1)[..., 0]
        loss = lse - picked
        return jnp.where(li == ignore_index, 0.0, loss)
    return apply(f, logits, label)


# ---------------------------------------------------------------------------
# fused LM-head matmul + cross entropy, chunked over the vocab
# ---------------------------------------------------------------------------
def _flce_impl(h, w, labels, chunk):
    """Online-logsumexp over vocab chunks: never materializes the full
    [N, V] logits in fp32 (the [B*S, 30k+] fp32 buffer is the single
    largest allocation in a BERT/GPT loss)."""
    N, H = h.shape
    V = w.shape[1]
    n_chunks = -(-V // chunk)
    Vp = n_chunks * chunk
    wp = jnp.pad(w, ((0, 0), (0, Vp - V)))
    w_chunks = wp.reshape(H, n_chunks, chunk).transpose(1, 0, 2)
    hf = h.astype(jnp.float32)
    li = labels.astype(jnp.int32)

    def body(carry, wc_i):
        m, s, picked = carry
        wc, i = wc_i
        z = (hf @ wc.astype(jnp.float32))              # [N, chunk] fp32
        base = i * chunk
        # mask padded vocab tail
        valid = (base + jnp.arange(chunk)) < V
        z = jnp.where(valid[None, :], z, -jnp.inf)
        m_new = jnp.maximum(m, z.max(-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            z - m_new[:, None]).sum(-1)
        in_chunk = (li >= base) & (li < base + chunk)
        local = jnp.clip(li - base, 0, chunk - 1)
        picked = picked + jnp.where(
            in_chunk, jnp.take_along_axis(z, local[:, None], 1)[:, 0], 0.0)
        return (m_new, s, picked), None

    init = (jnp.full((N,), -jnp.inf, jnp.float32),
            jnp.zeros((N,), jnp.float32), jnp.zeros((N,), jnp.float32))
    (m, s, picked), _ = jax.lax.scan(
        body, init, (w_chunks, jnp.arange(n_chunks)))
    return jnp.log(s) + m - picked, (m, s)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flce(h, w, labels, chunk):
    loss, _ = _flce_impl(h, w, labels, chunk)
    return loss


def _flce_fwd(h, w, labels, chunk):
    loss, (m, s) = _flce_impl(h, w, labels, chunk)
    return loss, (h, w, labels, m, s)


def _flce_bwd(chunk, res, g):
    h, w, labels, m, s = res
    N, H = h.shape
    V = w.shape[1]
    n_chunks = -(-V // chunk)
    Vp = n_chunks * chunk
    wp = jnp.pad(w, ((0, 0), (0, Vp - V)))
    w_chunks = wp.reshape(H, n_chunks, chunk).transpose(1, 0, 2)
    hf = h.astype(jnp.float32)
    li = labels.astype(jnp.int32)
    lse = jnp.log(s) + m
    gf = g.astype(jnp.float32)

    def body(dh, wc_i):
        wc, i = wc_i
        wcf = wc.astype(jnp.float32)
        z = hf @ wcf
        base = i * chunk
        valid = (base + jnp.arange(chunk)) < V
        p = jnp.where(valid[None, :], jnp.exp(z - lse[:, None]), 0.0)
        onehot = ((li[:, None] - base) ==
                  jnp.arange(chunk)[None, :]).astype(jnp.float32)
        dz = (p - onehot) * gf[:, None]               # [N, chunk]
        dh = dh + dz @ wcf.T
        dwc = hf.T @ dz                               # [H, chunk]
        return dh, dwc

    dh, dwcs = jax.lax.scan(body, jnp.zeros((N, H), jnp.float32),
                            (w_chunks, jnp.arange(n_chunks)))
    dw = dwcs.transpose(1, 0, 2).reshape(H, Vp)[:, :V]
    return dh.astype(h.dtype), dw.astype(w.dtype), None


_flce.defvjp(_flce_fwd, _flce_bwd)


def fused_linear_cross_entropy(hidden, weight, labels, chunk_size=8192):
    """loss = cross_entropy(hidden @ weight, labels), streamed over vocab
    chunks (TPU-native extension; the reference's closest analog is the
    fused softmax_with_cross_entropy_op.cc — this additionally fuses the
    LM-head matmul so the fp32 [N, V] logits never hit HBM at once).

    hidden [..., H], weight [H, V], labels [...] int. Returns per-token
    loss with hidden's leading shape.
    """
    def f(h, w, l):
        lead = h.shape[:-1]
        hf = h.reshape(-1, h.shape[-1])
        lf = l.reshape(-1)
        loss = _flce(hf, w, lf, chunk_size)
        return loss.reshape(lead)

    return apply(f, hidden, weight, labels)


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True):
    """[B, S, H, D] in, [B, S, H, D] out (paddle layout)."""
    if _use_pallas():
        if dropout_p > 0.0 and training:
            # attention dropout has no kernel path (rng-in-kernel is out of
            # scope); the one hot loop that sets it (BERT/ERNIE training)
            # should see this in the fallback counter, not run silently slow
            _note_fallback("flash_attention", "dropout")
        else:
            from .pallas import flash_attention as fa

            try:
                mesh, batch, tp = _mesh_axes()

                def pf(q, k, v, *mask):
                    m = mask[0] if mask else None
                    # an ambient mesh whose axes don't divide this call's
                    # geometry must not knock it off the kernel path: shed
                    # non-dividing axes and keep the (replicated) kernel
                    ba, hx = batch, tp
                    while ba and q.shape[0] % _axes_size(mesh, ba) != 0:
                        ba = ba[:-1]
                    if hx is not None and \
                            q.shape[2] % _axes_size(mesh, (hx,)) != 0:
                        hx = None
                    if mesh is not None and (ba or hx):
                        return fa.sharded_flash_attention(
                            q, k, v, mesh, head_axis=hx, batch_axes=ba,
                            causal=is_causal, mask=m)
                    return fa.flash_attention(q, k, v, causal=is_causal,
                                              mask=m)

                args = (query, key, value) + (
                    (attn_mask,) if attn_mask is not None else ())
                return apply(pf, *args)
            except Exception as e:  # noqa: BLE001 - counted, then composite
                _note_fallback("flash_attention", _fallback_reason(e))

    from ..framework import random as _random

    key_rng = _random.split_key() if (dropout_p > 0.0 and training) else None

    def f(q, k, v, *mask):
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
        # [B,S,H,D] -> [B,H,S,D]
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        logits = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
        if is_causal:
            s, t = logits.shape[-2], logits.shape[-1]
            cm = jnp.tril(jnp.ones((s, t), bool))
            logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
        if mask:
            m = mask[0]
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, jnp.asarray(-1e30, logits.dtype))
            else:
                logits = logits + m
        w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
        if key_rng is not None:
            keep = jax.random.bernoulli(key_rng, 1.0 - dropout_p, w.shape)
            w = jnp.where(keep, w / (1.0 - dropout_p), 0.0)
        out = jnp.einsum("bhst,bhtd->bhsd", w, vh)
        return jnp.swapaxes(out, 1, 2)

    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    return apply(f, *args)


# ---------------------------------------------------------------------------
# paged decode attention (fused_multi_transformer's masked decode analog):
# ragged Pallas kernel walking each lane's page-table row over the KV pool
# ---------------------------------------------------------------------------
def paged_decode_attention(q, k_pages, v_pages, rows, pos, seq_cap,
                           tp_axis=None):
    """Pallas paged decode attention over one layer's KV pool plane, or
    None when the kernel can't run (the caller keeps its dense-gather
    reference path and this shows up in the fallback counter).

    q [slots, 1, nh, hd] (the step's query, post-scatter); k_pages/v_pages
    [num_pages, page_size, nh, hd]; rows [slots, pages_per_slot] int32
    (-1 = unmapped); pos [slots] int32 inclusive extent; seq_cap static.
    Returns [slots, 1, nh, hd].  `tp_axis` names the mesh axis the pool's
    head dim is sharded over (the models' "mp" pin), if any.
    """
    if not _use_pallas():
        return None
    from .pallas import paged_attention as pa

    try:
        def pf(qv, kp, vp, rw, ps_):
            q1 = qv[:, 0]
            mesh = None
            if tp_axis is not None:
                mesh, _, _ = _mesh_axes()
            if mesh is not None and tp_axis in mesh.axis_names:
                out = pa.sharded_paged_decode_attention(
                    q1, kp, vp, rw, ps_, seq_cap, mesh, tp_axis)
            else:
                out = pa.paged_decode_attention(q1, kp, vp, rw, ps_, seq_cap)
            return out[:, None]

        return apply(pf, q, k_pages, v_pages, rows, pos)
    except Exception as e:  # noqa: BLE001 - counted, then dense gather
        _note_fallback("paged_attention", _fallback_reason(e))
        return None


# ---------------------------------------------------------------------------
# fused bias + GeLU (fused_gemm_epilogue intent): matmul stays with XLA's
# MXU scheduling, the bias-add + exact-erf GeLU epilogue runs as one Pallas
# pass (forward and backward) instead of separate elementwise HLOs
# ---------------------------------------------------------------------------
def _sharded_bias_gelu(v, b, mesh, batch, tp):
    """Pallas bias_gelu under shard_map so GSPMD keeps the FFN activation
    sharded (rows over dp/fsdp, feature columns over mp/tp) instead of
    gathering it around an opaque custom call."""
    from .pallas import bias_gelu as bg

    if batch and (v.ndim < 2 or not _rows_divisible(v.shape[0], mesh, batch)):
        batch = ()
    if tp is not None and v.shape[-1] % _axes_size(mesh, (tp,)) != 0:
        tp = None
    if not batch and tp is None:
        return bg.bias_gelu(v, b)

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    bspec = (batch if len(batch) > 1 else batch[0]) if batch else None
    vspec = P(bspec, *([None] * (v.ndim - 2)), tp)
    return shard_map(bg.bias_gelu, mesh=mesh,
                     in_specs=(vspec, P(tp)), out_specs=vspec,
                     check_rep=False)(v, b)


def _dropout(y, dropout_p, training):
    """Wrapper-level dropout keyed by the framework's per-step rng (the
    keep-mask is XLA elementwise and fuses into the surrounding matmul)."""
    if dropout_p <= 0.0 or not training:
        return y
    from ..framework import random as _random

    key_rng = _random.split_key()
    return apply(
        lambda v: jnp.where(
            jax.random.bernoulli(key_rng, 1.0 - dropout_p, v.shape),
            v / (1.0 - dropout_p), 0.0), y)


def bias_gelu(x, bias, dropout_p=0.0, training=True):
    """gelu(x + bias) (exact erf form), optionally followed by dropout
    threaded through the per-step rng.  Pallas-fused on TPU."""
    if _use_pallas():
        from .pallas import bias_gelu as bg

        try:
            mesh, batch, tp = _mesh_axes()

            def pf(v, b):
                if mesh is not None:
                    return _sharded_bias_gelu(v, b, mesh, batch, tp)
                return bg.bias_gelu(v, b)

            return _dropout(apply(pf, x, bias), dropout_p, training)
        except Exception as e:  # noqa: BLE001 - counted, then composite
            _note_fallback("bias_gelu", _fallback_reason(e))
    y = apply(lambda v, b: jax.nn.gelu(v + b.astype(v.dtype),
                                       approximate=False), x, bias)
    return _dropout(y, dropout_p, training)


def linear_bias_gelu(x, weight, bias, dropout_p=0.0, training=True):
    """gelu(x @ weight + bias): the FFN expansion matmul with its epilogue
    fused.  `bias` may be None (plain gelu of the matmul).  The matmul
    goes through the same AMP white_cast as nn.functional.linear."""
    from ..amp import white_cast

    y = apply(lambda v, w: jnp.matmul(*white_cast(v, w)), x, weight)
    if bias is None:
        return _dropout(
            apply(lambda v: jax.nn.gelu(v, approximate=False), y),
            dropout_p, training)
    return bias_gelu(y, bias, dropout_p=dropout_p, training=training)


# ---------------------------------------------------------------------------
# fused feedforward (fused_feedforward intent): LN -> linear -> act -> linear
# ---------------------------------------------------------------------------
def fused_feedforward(x, w1, b1, w2, b2, ln_scale=None, ln_bias=None,
                      activation="gelu", dropout_p=0.0, training=True,
                      pre_layer_norm=True, epsilon=1e-5):
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[activation]

    h = x
    if pre_layer_norm and ln_scale is not None:
        def pre(v, s, b):
            mean = jnp.mean(v, -1, keepdims=True)
            var = jnp.mean(jnp.square(v - mean), -1, keepdims=True)
            return (v - mean) * jax.lax.rsqrt(var + epsilon) * s + b
        h = apply(pre, x, ln_scale, ln_bias)
    if activation == "gelu":
        h = linear_bias_gelu(h, w1, b1, dropout_p=dropout_p,
                             training=training)
    else:
        h = _dropout(apply(lambda v, w1_, b1_: act(v @ w1_ + b1_),
                           h, w1, b1), dropout_p, training)
    h = apply(lambda v, w2_, b2_: v @ w2_ + b2_, h, w2, b2)
    out = apply(lambda v, r: v + r, x, h)
    if not pre_layer_norm and ln_scale is not None:
        def post(o, s, b):
            mean = jnp.mean(o, -1, keepdims=True)
            var = jnp.mean(jnp.square(o - mean), -1, keepdims=True)
            return (o - mean) * jax.lax.rsqrt(var + epsilon) * s + b
        out = apply(post, out, ln_scale, ln_bias)
    return out


def fused_embedding_layernorm(word_ids, pos_ids, type_ids, word_emb, pos_emb,
                              type_emb, ln_scale, ln_bias, epsilon=1e-5):
    """fused_embedding_eltwise_layernorm analog (BERT embedding fusion)."""
    def f(wi, pi, ti, we, pe, te, s, b):
        h = jnp.take(we, wi.astype(jnp.int32), 0) \
            + jnp.take(pe, pi.astype(jnp.int32), 0) \
            + jnp.take(te, ti.astype(jnp.int32), 0)
        mean = jnp.mean(h, -1, keepdims=True)
        var = jnp.mean(jnp.square(h - mean), -1, keepdims=True)
        return (h - mean) * jax.lax.rsqrt(var + epsilon) * s + b
    return apply(f, word_ids, pos_ids, type_ids, word_emb, pos_emb, type_emb,
                 ln_scale, ln_bias)
