"""Pallas TPU kernels for the fused transformer path (SURVEY.md §7 step 8)."""
