"""Pallas TPU kernels for the fused transformer path (SURVEY.md §7 step 8)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as _pltpu

# jax renamed TPUCompilerParams -> CompilerParams across releases; kernels
# import the resolved class from here so they compile against either name.
CompilerParams = getattr(_pltpu, "CompilerParams", None) \
    or getattr(_pltpu, "TPUCompilerParams")


def interpret_default() -> bool:
    """Pallas kernels interpret on CPU (tests), compile via Mosaic on TPU."""
    return jax.default_backend() == "cpu"


def im(f):
    """Index-map wrapper forcing literal ints to i32 (the framework enables
    jax_enable_x64 for float64 API parity; Mosaic rejects i64 block indices)."""
    def g(*idx):
        return tuple(jnp.int32(v) if isinstance(v, int) else v
                     for v in f(*idx))
    return g
