"""Pallas TPU fused bias + GeLU (forward + backward in-kernel).

Reference analog: the fused_feedforward / fused_gemm_epilogue CUDA
epilogues — bias add and activation applied in the matmul's epilogue
instead of as separate HBM round-trips.  Here the matmul stays with XLA
(the MXU path XLA already schedules well) and this kernel fuses what XLA
keeps as separate elementwise HLOs under x64: one read of the activation
input produces gelu(x + b), and the backward kernel recomputes u = x + b
to emit dy * gelu'(u) in a single pass (db is the row-sum of dx, left to
XLA's reduction).

GeLU is the exact erf form (matches nn.functional.gelu's default
approximate=False).  All math in float32.  Dropout is NOT in-kernel: the
wrapper in ops/fused.py threads the per-step rng and applies the keep-mask
as XLA elementwise ops, which fuse into the surrounding matmul anyway.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_INV_SQRT2 = 0.7071067811865476
_INV_SQRT_2PI = 0.3989422804014327

from . import im as _im, interpret_default as _interpret_default


def _gelu_f32(u):
    return 0.5 * u * (1.0 + jax.lax.erf(u * _INV_SQRT2))


def _dgelu_f32(u):
    cdf = 0.5 * (1.0 + jax.lax.erf(u * _INV_SQRT2))
    pdf = jnp.exp(-0.5 * u * u) * _INV_SQRT_2PI
    return cdf + u * pdf


def _fwd_kernel(x_ref, b_ref, y_ref):
    u = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = _gelu_f32(u).astype(y_ref.dtype)


def _bwd_kernel(x_ref, b_ref, dy_ref, dx_ref):
    u = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    dx = dy_ref[...].astype(jnp.float32) * _dgelu_f32(u)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _pick_block_rows(r: int) -> int:
    for cand in (256, 128, 64, 32, 16, 8):
        if r % cand == 0:
            return cand
    return 0


def _row_call(kernel, outs, x2d, b, extra, interpret):
    r, n = x2d.shape
    block_r = _pick_block_rows(r)
    row_spec = pl.BlockSpec((block_r, n), _im(lambda i: (i, 0)))
    vec_spec = pl.BlockSpec((n,), _im(lambda i: (0,)))
    return pl.pallas_call(
        kernel,
        grid=(r // block_r,),
        in_specs=[row_spec, vec_spec] + [row_spec] * len(extra),
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((r, n), outs),
        interpret=interpret,
    )(x2d, b, *extra)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _bg(x2d, b, interpret):
    return _row_call(_fwd_kernel, x2d.dtype, x2d, b, (), interpret)


def _bg_fwd(x2d, b, interpret):
    return _bg(x2d, b, interpret), (x2d, b)


def _bg_bwd(interpret, res, dy):
    x2d, b = res
    dx = _row_call(_bwd_kernel, x2d.dtype, x2d, b, (dy,), interpret)
    # d/db == d/dx elementwise (y = gelu(x + b)), so db is dx's row-sum
    db = jnp.sum(dx.astype(jnp.float32), axis=0).astype(b.dtype)
    return dx, db


_bg.defvjp(_bg_fwd, _bg_bwd)


def bias_gelu(x, bias, interpret: bool | None = None):
    """gelu(x + bias) over the last dim; any leading shape.

    x [..., F], bias [F].  Raises NotImplementedError for rows not
    tileable to 8 sublanes (caller falls back to XLA).
    """
    n = x.shape[-1]
    if bias.shape != (n,):
        raise NotImplementedError(
            f"bias_gelu: bias {bias.shape} must be 1D of size {n}")
    lead = x.shape[:-1]
    x2d = x.reshape(-1, n)
    if _pick_block_rows(x2d.shape[0]) == 0:
        raise NotImplementedError(
            f"bias_gelu: rows {x2d.shape[0]} not divisible by 8")
    if interpret is None:
        interpret = _interpret_default()
    return _bg(x2d, bias, interpret).reshape(*lead, n)
