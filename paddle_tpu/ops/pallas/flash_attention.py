"""Pallas TPU flash attention (forward + backward).

The fused-attention op of the framework (reference analogs:
paddle/fluid/operators/fused/multihead_matmul_op.cu and
math/bert_encoder_functor.cu — those are inference-only CUDA fusions; this
kernel is the training-grade TPU replacement named as intent by
BASELINE.json's fused_attention).

Design (flash attention v2 style):
- public entry takes paddle layout [B, S, H, D]; internally folds to
  [B*H, S, D] and tiles the MXU with (block_q x D) @ (D x block_k) matmuls.
- forward: grid (BH, num_q, num_k) with the KV dimension innermost;
  running max `m`, normalizer `l`, and the output accumulator live in VMEM
  scratch across KV steps; output + logsumexp written on the last KV step.
- backward: two kernels — dq (grid over KV innermost) and dkv (grid over Q
  innermost) — recomputing p = exp(s - lse) per tile, FLOPs ~ 2.5x fwd.
- causal: fully-masked tiles are skipped with pl.when (no FLOPs), the
  diagonal tile is masked with a broadcasted iota comparison.
- all accumulation in float32 regardless of input dtype (bf16 in, f32 acc).

Falls back (by raising) to the XLA softmax path in ops/fused.py when shapes
don't tile (seq not divisible by block) — the caller catches.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


from . import (CompilerParams as _CompilerParams, im as _im,
               interpret_default as _interpret_default)


def _dot(a, b, contract):
    return jax.lax.dot_general(a, b, (contract, ((), ())),
                               preferred_element_type=jnp.float32)


def _causal_mask(q_idx, k_idx, block_q, block_k):
    q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos >= k_pos


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal,
                block_q, block_k, num_k):
    q_idx, k_idx = pl.program_id(1), pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: tiles entirely above the diagonal contribute nothing
    run = (q_idx + 1) * block_q > k_idx * block_k if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = _dot(q, k, ((1,), (1,))) * sm_scale  # [bq, bk] f32
        if causal:
            s = jnp.where(_causal_mask(q_idx, k_idx, block_q, block_k),
                          s, _NEG_INF)

        m_prev = m_ref[:, :1]                      # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        acc_ref[...] = acc_ref[...] * alpha + _dot(
            p.astype(v_ref.dtype), v_ref[0], ((1,), (0,)))
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(k_idx == num_k - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, ...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # lse broadcast over a 128-lane minor dim (TPU tiling-friendly)
        lse_ref[0, ...] = m_ref[...] + jnp.log(l_safe)


def _fwd_call(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    num_q, num_k = s_q // block_q, s_k // block_k

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, i, 0))),
            pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, j, 0))),
            pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, j, 0))),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, i, 0))),
            pl.BlockSpec((1, block_q, 128), _im(lambda b, i, j: (b, i, 0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_q, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    # keep only one lane as the residual (128x smaller in HBM; the lane
    # broadcast is a Mosaic tiling requirement, not information)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, sm_scale, causal, block_q, block_k, num_k):
    q_idx, k_idx = pl.program_id(1), pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (q_idx + 1) * block_q > k_idx * block_k if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                    # [bq, 1]
        delta = delta_ref[0][:, :1]

        s = _dot(q, k, ((1,), (1,))) * sm_scale
        if causal:
            s = jnp.where(_causal_mask(q_idx, k_idx, block_q, block_k),
                          s, _NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk] f32
        dp = _dot(do, v, ((1,), (1,)))             # [bq, bk]
        ds = p * (dp - delta) * sm_scale
        acc_ref[...] += _dot(ds.astype(k.dtype), k, ((1,), (0,)))

    @pl.when(k_idx == num_k - 1)
    def _finish():
        dq_ref[0, ...] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                block_q, block_k, num_q):
    k_idx, q_idx = pl.program_id(1), pl.program_id(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (q_idx + 1) * block_q > k_idx * block_k if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]

        s = _dot(q, k, ((1,), (1,))) * sm_scale    # [bq, bk]
        if causal:
            s = jnp.where(_causal_mask(q_idx, k_idx, block_q, block_k),
                          s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv_acc[...] += _dot(p.astype(do.dtype), do, ((0,), (0,)))
        dp = _dot(do, v, ((1,), (1,)))
        ds = p * (dp - delta) * sm_scale           # [bq, bk]
        dk_acc[...] += _dot(ds.astype(q.dtype), q, ((0,), (0,)))

    @pl.when(q_idx == num_q - 1)
    def _finish():
        dk_ref[0, ...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k,
              interpret):
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    num_q, num_k = s_q // block_q, s_k // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                       # [bh, s_q]
    # Mosaic requires >=8 sublanes on row blocks, so row vectors enter the
    # kernels broadcast over a 128-lane minor dim (transient in bwd only;
    # the saved fwd residual is the compact [bh, s_q]).
    lse_r = jnp.broadcast_to(lse[..., None], (bh, s_q, 128))
    delta_r = jnp.broadcast_to(delta[..., None], (bh, s_q, 128))

    q_spec = pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, i, 0)))
    k_spec_j = pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, j, 0)))
    row_spec = pl.BlockSpec((1, block_q, 128), _im(lambda b, i, j: (b, i, 0)))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k=num_k),
        grid=(bh, num_q, num_k),
        in_specs=[q_spec, k_spec_j, k_spec_j, q_spec, row_spec, row_spec],
        out_specs=pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, i, 0))),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse_r, delta_r)

    # dkv: grid is (bh, num_k, num_q) — q innermost
    q_spec_j = pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, j, 0)))
    k_spec_i = pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, i, 0)))
    row_spec_j = pl.BlockSpec((1, block_q, 128), _im(lambda b, i, j: (b, j, 0)))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=num_q),
        grid=(bh, num_k, num_q),
        in_specs=[q_spec_j, k_spec_i, k_spec_i, q_spec_j, row_spec_j,
                  row_spec_j],
        out_specs=[
            pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, i, 0))),
            pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, i, 0))),
        ],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse_r, delta_r)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp entry over [BH, S, D]
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _mha(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _fwd_call(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return out


def _mha_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _fwd_call(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret)
    return out, (q, k, v, out, lse)


def _mha_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd_call(q, k, v, o, lse, do, causal, sm_scale,
                           block_q, block_k, interpret)
    return dq, dk, dv


_mha.defvjp(_mha_fwd, _mha_bwd)


def flash_attention(q, k, v, causal: bool = False, sm_scale=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None):
    """Flash attention over paddle layout [B, S, H, D] -> [B, S, H, D].

    Raises NotImplementedError for shapes the kernel doesn't tile
    (caller falls back to the XLA path).
    """
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    if s_q % block_q or s_k % block_k:
        raise NotImplementedError(
            f"flash_attention: seq ({s_q},{s_k}) not divisible by blocks "
            f"({block_q},{block_k})")
    if min(block_q, block_k) < 8:
        raise NotImplementedError("flash_attention: sequence too short")
    if k.shape[2] != h:
        raise NotImplementedError("flash_attention: GQA head mismatch")
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _interpret_default()

    def fold(x, s):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)

    out = _mha(fold(q, s_q), fold(k, s_k), fold(v, s_k), causal,
               float(sm_scale), block_q, block_k, interpret)
    return jnp.swapaxes(out.reshape(b, h, s_q, d), 1, 2)
