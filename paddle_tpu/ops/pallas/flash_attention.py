"""Pallas TPU flash attention (forward + backward, causal + additive mask).

The fused-attention op of the framework (reference analogs:
paddle/fluid/operators/fused/multihead_matmul_op.cu and
math/bert_encoder_functor.cu — those are inference-only CUDA fusions; this
kernel is the training-grade TPU replacement named as intent by
BASELINE.json's fused_attention).

Design (flash attention v2 style):
- public entry takes paddle layout [B, S, H, D]; internally folds to
  [B*H, S, D] and tiles the MXU with (block_q x D) @ (D x block_k) matmuls.
- forward: grid (BH, num_q, num_k) with the KV dimension innermost;
  running max `m`, normalizer `l`, and the output accumulator live in VMEM
  scratch across KV steps; output + logsumexp written on the last KV step.
- backward: two kernels — dq (grid over KV innermost) and dkv (grid over Q
  innermost) — recomputing p = exp(s - lse) per tile, FLOPs ~ 2.5x fwd.
- causal: fully-masked tiles are skipped with pl.when (no FLOPs), the
  diagonal tile is masked with a broadcasted iota comparison.
- mask: an additive bias broadcastable to [B, H, S_q, S_k] (bool masks are
  converted to 0 / -1e30 by the wrapper) streamed tile-by-tile into the
  score matmul of all three kernels — the padding / attention-mask path of
  MultiHeadAttention runs through the kernel instead of falling back.  The
  mask is DATA, not a parameter: its cotangent is defined as zero (a
  learned attention bias would need the [BH, S, S] ds write-back this
  kernel deliberately avoids).
- all accumulation in float32 regardless of input dtype (bf16 in, f32 acc).

Sharding: `sharded_flash_attention` wraps the kernel in shard_map over the
mesh's head (tp/mp) and batch (dp/fsdp) axes so GSPMD runs one kernel per
shard with the LOCAL head count — attention has no cross-head or
cross-batch reduction, so no collectives are needed inside the body.

Falls back (by raising) to the XLA softmax path in ops/fused.py when shapes
don't tile (seq not divisible by block) — the caller catches.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


from . import (CompilerParams as _CompilerParams, im as _im,
               interpret_default as _interpret_default)


def _dot(a, b, contract):
    return jax.lax.dot_general(a, b, (contract, ((), ())),
                               preferred_element_type=jnp.float32)


def _causal_mask(q_idx, k_idx, block_q, block_k):
    q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos >= k_pos


def _scores(q, k, bias_ref, q_idx, k_idx, *, sm_scale, causal,
            block_q, block_k):
    """The shared score tile: scale, additive mask, causal mask."""
    s = _dot(q, k, ((1,), (1,))) * sm_scale        # [bq, bk] f32
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    if causal:
        s = jnp.where(_causal_mask(q_idx, k_idx, block_q, block_k),
                      s, _NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(*refs, sm_scale, causal, has_bias, block_q, block_k, num_k):
    if has_bias:
        q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, acc_ref, m_ref, \
            l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        bias_ref = None
    q_idx, k_idx = pl.program_id(1), pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: tiles entirely above the diagonal contribute nothing
    run = (q_idx + 1) * block_q > k_idx * block_k if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = _scores(q, k, bias_ref, q_idx, k_idx, sm_scale=sm_scale,
                    causal=causal, block_q=block_q, block_k=block_k)

        m_prev = m_ref[:, :1]                      # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        acc_ref[...] = acc_ref[...] * alpha + _dot(
            p.astype(v_ref.dtype), v_ref[0], ((1,), (0,)))
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(k_idx == num_k - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, ...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # lse broadcast over a 128-lane minor dim (TPU tiling-friendly)
        lse_ref[0, ...] = m_ref[...] + jnp.log(l_safe)


def _bias_group(bh: int, bias) -> int:
    """How many grid-b values share one bias plane (bias folded to
    [B*Hm, S_q, S_k]; group == H when the mask is per-batch only)."""
    return bh // bias.shape[0]


def _fwd_call(q, k, v, bias, causal, sm_scale, block_q, block_k, interpret):
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    num_q, num_k = s_q // block_q, s_k // block_k
    has_bias = bias is not None

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, has_bias=has_bias,
        block_q=block_q, block_k=block_k, num_k=num_k)

    in_specs = [
        pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, i, 0))),
        pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, j, 0))),
        pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, j, 0))),
    ]
    operands = [q, k, v]
    if has_bias:
        g = _bias_group(bh, bias)
        in_specs.append(pl.BlockSpec(
            (1, block_q, block_k), _im(lambda b, i, j: (b // g, i, j))))
        operands.append(bias)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, i, 0))),
            pl.BlockSpec((1, block_q, 128), _im(lambda b, i, j: (b, i, 0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_q, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)
    # keep only one lane as the residual (128x smaller in HBM; the lane
    # broadcast is a Mosaic tiling requirement, not information)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _dq_kernel(*refs, sm_scale, causal, has_bias, block_q, block_k, num_k):
    if has_bias:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref, \
            dq_ref, acc_ref = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, \
            acc_ref = refs
        bias_ref = None
    q_idx, k_idx = pl.program_id(1), pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (q_idx + 1) * block_q > k_idx * block_k if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                    # [bq, 1]
        delta = delta_ref[0][:, :1]

        s = _scores(q, k, bias_ref, q_idx, k_idx, sm_scale=sm_scale,
                    causal=causal, block_q=block_q, block_k=block_k)
        p = jnp.exp(s - lse)                       # [bq, bk] f32
        dp = _dot(do, v, ((1,), (1,)))             # [bq, bk]
        ds = p * (dp - delta) * sm_scale
        acc_ref[...] += _dot(ds.astype(k.dtype), k, ((1,), (0,)))

    @pl.when(k_idx == num_k - 1)
    def _finish():
        dq_ref[0, ...] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(*refs, sm_scale, causal, has_bias, block_q, block_k, num_q):
    if has_bias:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref, \
            dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, \
            dk_acc, dv_acc = refs
        bias_ref = None
    k_idx, q_idx = pl.program_id(1), pl.program_id(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (q_idx + 1) * block_q > k_idx * block_k if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]

        s = _scores(q, k, bias_ref, q_idx, k_idx, sm_scale=sm_scale,
                    causal=causal, block_q=block_q, block_k=block_k)
        p = jnp.exp(s - lse)
        dv_acc[...] += _dot(p.astype(do.dtype), do, ((0,), (0,)))
        dp = _dot(do, v, ((1,), (1,)))
        ds = p * (dp - delta) * sm_scale           # [bq, bk]
        dk_acc[...] += _dot(ds.astype(q.dtype), q, ((0,), (0,)))

    @pl.when(q_idx == num_q - 1)
    def _finish():
        dk_ref[0, ...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, bias, causal, sm_scale, block_q, block_k,
              interpret):
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    num_q, num_k = s_q // block_q, s_k // block_k
    has_bias = bias is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                       # [bh, s_q]
    # Mosaic requires >=8 sublanes on row blocks, so row vectors enter the
    # kernels broadcast over a 128-lane minor dim (transient in bwd only;
    # the saved fwd residual is the compact [bh, s_q]).
    lse_r = jnp.broadcast_to(lse[..., None], (bh, s_q, 128))
    delta_r = jnp.broadcast_to(delta[..., None], (bh, s_q, 128))

    q_spec = pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, i, 0)))
    k_spec_j = pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, j, 0)))
    row_spec = pl.BlockSpec((1, block_q, 128), _im(lambda b, i, j: (b, i, 0)))

    dq_in_specs = [q_spec, k_spec_j, k_spec_j, q_spec, row_spec, row_spec]
    dq_operands = [q, k, v, do, lse_r, delta_r]
    if has_bias:
        g = _bias_group(bh, bias)
        dq_in_specs.append(pl.BlockSpec(
            (1, block_q, block_k), _im(lambda b, i, j: (b // g, i, j))))
        dq_operands.append(bias)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          has_bias=has_bias, block_q=block_q,
                          block_k=block_k, num_k=num_k),
        grid=(bh, num_q, num_k),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, i, 0))),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dq_operands)

    # dkv: grid is (bh, num_k, num_q) — q innermost
    q_spec_j = pl.BlockSpec((1, block_q, d), _im(lambda b, i, j: (b, j, 0)))
    k_spec_i = pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, i, 0)))
    row_spec_j = pl.BlockSpec((1, block_q, 128), _im(lambda b, i, j: (b, j, 0)))
    dkv_in_specs = [q_spec_j, k_spec_i, k_spec_i, q_spec_j, row_spec_j,
                    row_spec_j]
    dkv_operands = [q, k, v, do, lse_r, delta_r]
    if has_bias:
        g = _bias_group(bh, bias)
        # grid here is (b, k_idx=i, q_idx=j): bias tile rows follow j
        dkv_in_specs.append(pl.BlockSpec(
            (1, block_q, block_k), _im(lambda b, i, j: (b // g, j, i))))
        dkv_operands.append(bias)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          has_bias=has_bias, block_q=block_q,
                          block_k=block_k, num_q=num_q),
        grid=(bh, num_k, num_q),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, i, 0))),
            pl.BlockSpec((1, block_k, d), _im(lambda b, i, j: (b, i, 0))),
        ],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dkv_operands)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp entries over [BH, S, D] (+ folded bias [B*Hm, S_q, S_k])
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _mha(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _fwd_call(q, k, v, None, causal, sm_scale, block_q, block_k,
                       interpret)
    return out


def _mha_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _fwd_call(q, k, v, None, causal, sm_scale, block_q, block_k,
                         interpret)
    return out, (q, k, v, out, lse)


def _mha_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd_call(q, k, v, o, lse, do, None, causal, sm_scale,
                           block_q, block_k, interpret)
    return dq, dk, dv


_mha.defvjp(_mha_fwd, _mha_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _mha_masked(q, k, v, bias, causal, sm_scale, block_q, block_k,
                interpret):
    out, _ = _fwd_call(q, k, v, bias, causal, sm_scale, block_q, block_k,
                       interpret)
    return out


def _mha_masked_fwd(q, k, v, bias, causal, sm_scale, block_q, block_k,
                    interpret):
    out, lse = _fwd_call(q, k, v, bias, causal, sm_scale, block_q, block_k,
                         interpret)
    return out, (q, k, v, bias, out, lse)


def _mha_masked_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, bias, o, lse = res
    dq, dk, dv = _bwd_call(q, k, v, o, lse, do, bias, causal, sm_scale,
                           block_q, block_k, interpret)
    # the mask is data (padding/visibility), not a parameter: its
    # cotangent is defined as zero (see module docstring)
    return dq, dk, dv, jnp.zeros_like(bias)


_mha_masked.defvjp(_mha_masked_fwd, _mha_masked_bwd)


def _fold_mask(mask, b, h, s_q, s_k):
    """Normalize a bool/additive mask broadcastable to [B, H, S_q, S_k]
    into the folded additive bias [B*Hm, S_q, S_k] (Hm in {1, H})."""
    m = mask
    if m.dtype == jnp.bool_:
        m = jnp.where(m, 0.0, _NEG_INF)
    m = m.astype(jnp.float32)
    while m.ndim < 4:
        m = m[None]
    if m.ndim != 4:
        raise NotImplementedError(
            f"flash_attention: mask rank {mask.ndim} unsupported")
    hm = h if m.shape[1] != 1 else 1
    try:
        m = jnp.broadcast_to(m, (b, hm, s_q, s_k))
    except ValueError:
        raise NotImplementedError(
            f"flash_attention: mask shape {mask.shape} does not broadcast "
            f"to ({b}, {h}, {s_q}, {s_k})")
    return m.reshape(b * hm, s_q, s_k)


def flash_attention(q, k, v, causal: bool = False, sm_scale=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None, mask=None):
    """Flash attention over paddle layout [B, S, H, D] -> [B, S, H, D].

    ``mask`` is a bool (True = attend) or additive mask broadcastable to
    [B, H, S_q, S_k], composable with ``causal``.  Raises
    NotImplementedError for shapes the kernel doesn't tile (caller falls
    back to the XLA path).
    """
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    if s_q % block_q or s_k % block_k:
        raise NotImplementedError(
            f"flash_attention: seq ({s_q},{s_k}) not divisible by blocks "
            f"({block_q},{block_k})")
    if min(block_q, block_k) < 8:
        raise NotImplementedError("flash_attention: sequence too short")
    if k.shape[2] != h:
        raise NotImplementedError("flash_attention: GQA head mismatch")
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _interpret_default()

    def fold(x, s):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)

    if mask is None:
        out = _mha(fold(q, s_q), fold(k, s_k), fold(v, s_k), causal,
                   float(sm_scale), block_q, block_k, interpret)
    else:
        bias = _fold_mask(mask, b, h, s_q, s_k)
        out = _mha_masked(fold(q, s_q), fold(k, s_k), fold(v, s_k), bias,
                          causal, float(sm_scale), block_q, block_k,
                          interpret)
    return jnp.swapaxes(out.reshape(b, h, s_q, d), 1, 2)


# ---------------------------------------------------------------------------
# GSPMD composition: one kernel per shard via shard_map
# ---------------------------------------------------------------------------
def sharded_flash_attention(q, k, v, mesh, head_axis=None, batch_axes=(),
                            causal: bool = False, sm_scale=None,
                            block_q: int = DEFAULT_BLOCK_Q,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: bool | None = None, mask=None):
    """flash_attention under shard_map over ``mesh``: heads split over
    ``head_axis`` (tp/mp), batch over ``batch_axes`` (dp/fsdp) — the
    head-dim blocking inside each shard sees the LOCAL (sharded) head
    count, so `mesh3d` runs the kernel rather than falling back to one
    replicated call.  Axes absent from the mesh or not dividing the
    operand raise NotImplementedError (caller falls back)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    batch_axes = tuple(a for a in batch_axes
                       if sizes.get(a, 1) > 1)
    if head_axis is not None and sizes.get(head_axis, 1) <= 1:
        head_axis = None
    tp = sizes.get(head_axis, 1) if head_axis else 1
    nb = 1
    for a in batch_axes:
        nb *= sizes[a]
    if h % tp or b % nb:
        raise NotImplementedError(
            f"sharded flash_attention: heads {h} % tp {tp} or batch {b} % "
            f"dp {nb} != 0")
    if not batch_axes and head_axis is None:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret, mask=mask)
    bspec = tuple(batch_axes) if len(batch_axes) > 1 else \
        (batch_axes[0] if batch_axes else None)
    qkv_spec = P(bspec, None, head_axis, None)
    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    operands = [q, k, v]
    if mask is not None:
        m = mask
        if m.dtype == jnp.bool_:
            m = jnp.where(m, 0.0, _NEG_INF)
        m = m.astype(jnp.float32)
        while m.ndim < 4:
            m = m[None]
        hm = h if m.shape[1] not in (1,) else 1
        m = jnp.broadcast_to(m, (b, hm, s_q, s_k))
        in_specs.append(P(bspec, head_axis if hm == h else None, None, None))
        operands.append(m)

    def body(ql, kl, vl, *rest):
        return flash_attention(ql, kl, vl, causal=causal, sm_scale=sm_scale,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret,
                               mask=rest[0] if rest else None)

    f = shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                  out_specs=qkv_spec, check_rep=False)
    return f(*operands)
