"""Pallas flash-attention kernel (placeholder until the TPU kernel lands;
ops/fused.py falls back to the XLA softmax path on NotImplementedError)."""


def flash_attention(q, k, v, causal=False):
    raise NotImplementedError("pallas flash attention kernel pending")
