"""Pallas fused layer-norm kernel (placeholder until the TPU kernel lands;
ops/fused.py falls back to the XLA composite on NotImplementedError)."""


def layer_norm(x, weight, bias, epsilon=1e-5):
    raise NotImplementedError("pallas layer_norm kernel pending")
