"""Pallas TPU paged decode attention (ragged, page-table indirected).

The serving-side half of the fused-attention story (reference analog:
fused_multi_transformer_op.cu's masked decode attention — that kernel reads
a dense [B, S_max] cache; this one reads the paged KV pool of
serving/kv_cache.py directly).

One query token per lane attends over that lane's pages, walked through its
int32 page-table row — the pool is never gathered into a dense
``[slots, S_max]`` view.  The page table and per-lane positions ride in as
scalar-prefetch operands (pltpu.PrefetchScalarGridSpec), so the KV
BlockSpec index maps pick each grid step's page straight from the table
and Mosaic can start the HBM->VMEM fetch of page ``rows[lane, p]`` while
the previous page is still being processed.

Grid is (slots, pages_walked): for each lane the kernel runs the flash
running-softmax (m/l/acc in VMEM scratch) across its pages; pages that are
unmapped (table entry -1) or entirely past the lane's position are skipped
with pl.when (no FLOPs, and the index map clamps their page id to 0 so no
out-of-bounds fetch is issued).  Within the last live page, tokens beyond
``pos`` are masked to -1e30 — matching the dense reference's validity mask
exactly, token by token.

Used by GPTAttention.decode_pages through ops/fused.py when
FLAGS_use_pallas_kernels is on; the dense-gather path stays as the
fallback and parity reference.  The kernel only READS the pool (the
current token's K/V scatter stays an XLA `.at[].set` before the call), so
it composes with the engine's buffer donation untouched.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

from . import interpret_default as _interpret_default


def _kernel(rows_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
            acc_ref, m_ref, l_ref, *, sm_scale, page_size, pages_walked):
    lane, p_idx = pl.program_id(0), pl.program_id(1)

    @pl.when(p_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    page = rows_ref[lane, p_idx]
    pos = pos_ref[lane]
    # a page contributes iff it is mapped and starts at or before pos
    live = (page >= 0) & (p_idx * page_size <= pos)

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32) * sm_scale      # [nh, hd]
        k = k_ref[0].astype(jnp.float32)                 # [ps, nh, hd]
        # per-head q . k over hd: batch nh, contract hd -> [nh, ps]
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        tok = p_idx * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(tok <= pos, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                           # [nh, ps]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)                 # [ps, nh, hd]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)          # [nh, hd]
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p_idx == pages_walked - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, ...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, rows, pos, seq_cap: int,
                           sm_scale=None, interpret: bool | None = None):
    """Ragged decode attention over the paged KV pool.

    q: [slots, nh, hd] (one token per lane); k_pages/v_pages:
    [num_pages, page_size, nh, hd] (one layer's pool plane, AFTER the
    current token's scatter); rows: [slots, pages_per_slot] int32 page
    table (-1 = unmapped); pos: [slots] int32 attention extent per lane
    (inclusive); seq_cap: STATIC max extent — only ceil(seq_cap /
    page_size) table columns are walked.  Returns [slots, nh, hd] in
    q's dtype.  Raises NotImplementedError for untileable geometry
    (caller falls back to the dense gather).
    """
    slots, nh, hd = q.shape
    num_pages, ps = k_pages.shape[0], k_pages.shape[1]
    if k_pages.shape[2] != nh or k_pages.shape[3] != hd:
        raise NotImplementedError(
            f"paged_decode_attention: pool heads {k_pages.shape[2:]} != "
            f"query heads ({nh}, {hd})")
    pages_walked = -(-int(seq_cap) // ps)
    if pages_walked > rows.shape[1]:
        raise NotImplementedError(
            f"paged_decode_attention: seq_cap {seq_cap} needs "
            f"{pages_walked} pages > table width {rows.shape[1]}")
    if ps < 8:
        raise NotImplementedError(
            f"paged_decode_attention: page_size {ps} < 8 sublanes")
    if sm_scale is None:
        sm_scale = 1.0 / (hd ** 0.5)
    if interpret is None:
        interpret = _interpret_default()

    rows = jnp.asarray(rows, jnp.int32)
    pos = jnp.asarray(pos, jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, pages_walked),
        in_specs=[
            pl.BlockSpec((1, nh, hd),
                         lambda l, p, rows, pos: (l, 0, 0)),
            # dead (unmapped / past-pos) pages clamp to page 0: the fetch
            # target must be in-bounds even though pl.when skips the math
            pl.BlockSpec((1, ps, nh, hd),
                         lambda l, p, rows, pos:
                         (jnp.maximum(rows[l, p], 0), 0, 0, 0)),
            pl.BlockSpec((1, ps, nh, hd),
                         lambda l, p, rows, pos:
                         (jnp.maximum(rows[l, p], 0), 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, hd),
                               lambda l, p, rows, pos: (l, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, hd), jnp.float32),
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, sm_scale=float(sm_scale), page_size=ps,
                          pages_walked=pages_walked),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, nh, hd), q.dtype),
        interpret=interpret,
    )(rows, pos, q, k_pages, v_pages)
    return out


def sharded_paged_decode_attention(q, k_pages, v_pages, rows, pos,
                                   seq_cap: int, mesh, head_axis,
                                   sm_scale=None,
                                   interpret: bool | None = None):
    """paged_decode_attention under shard_map: the pool's head axis is
    sharded over ``head_axis`` (layout.kv_page_spec() / the models' "mp"
    pin), the page table and positions are replicated, and each shard
    runs the kernel on its LOCAL heads — decode attention has no
    cross-head reduction, so no collectives are needed."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    nh = q.shape[1]
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    tp = sizes.get(head_axis, 1)
    if tp <= 1:
        return paged_decode_attention(q, k_pages, v_pages, rows, pos,
                                      seq_cap, sm_scale=sm_scale,
                                      interpret=interpret)
    if nh % tp:
        raise NotImplementedError(
            f"sharded paged_decode_attention: heads {nh} % tp {tp} != 0")

    def body(ql, kl, vl, rl, pl_):
        return paged_decode_attention(ql, kl, vl, rl, pl_, seq_cap,
                                      sm_scale=sm_scale,
                                      interpret=interpret)

    f = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, head_axis, None),
                  P(None, None, head_axis, None),
                  P(None, None, head_axis, None),
                  P(None, None), P(None)),
        out_specs=P(None, head_axis, None), check_rep=False)
    return f(q, k_pages, v_pages, rows, pos)
