"""Pallas TPU fused softmax cross-entropy (log-softmax + label gather,
forward AND backward in-kernel).

Reference analog: softmax_with_cross_entropy_op.cu — the fused loss that
kept Fluid's LM heads from materializing log-probabilities.  The XLA
composite in ops/fused.py computes max / lse / gather as separate HBM
passes over the [N, V] logits; this kernel streams each row tile once per
pass with the running max / normalizer / picked-logit in VMEM scratch
(vocab innermost, flash-style online logsumexp), and the backward kernel
forms (softmax - onehot) * g tile-by-tile without a resident [N, V]
softmax.

Hard labels only (soft_label=False — the ops/fused.py gate routes soft
labels to XLA); `ignore_index` rows produce loss 0 and gradient 0.  The
label gather is a one-hot select against a broadcasted iota (TPU has no
in-kernel gather).  The vocab axis is padded to a lane multiple (128) with
-1e30 by the wrapper — exp underflows to exactly 0, so padding never
perturbs the loss; padded rows carry ignore_index.  All math in float32
regardless of input dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

from . import (CompilerParams as _CompilerParams, im as _im,
               interpret_default as _interpret_default)


def _fwd_kernel(z_ref, lab_ref, loss_ref, lse_ref, m_ref, l_ref, pick_ref,
                *, block_c, num_c, ignore_index):
    c_idx = pl.program_id(1)

    @pl.when(c_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        pick_ref[...] = jnp.zeros_like(pick_ref)

    z = z_ref[...].astype(jnp.float32)                 # [br, bc]
    lab = lab_ref[...]                                 # [br] int32
    col = c_idx * block_c + jax.lax.broadcasted_iota(
        jnp.int32, z.shape, 1)
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(z, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    l_new = jnp.exp(m_prev - m_new) * l_prev + \
        jnp.sum(jnp.exp(z - m_new), axis=-1, keepdims=True)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
    picked = jnp.sum(jnp.where(col == lab[:, None], z, 0.0),
                     axis=-1, keepdims=True)
    pick_ref[...] += jnp.broadcast_to(picked, pick_ref.shape)

    @pl.when(c_idx == num_c - 1)
    def _finish():
        lse = m_ref[:, :1] + jnp.log(l_ref[:, :1])
        loss = lse - pick_ref[:, :1]
        loss = jnp.where((lab == ignore_index)[:, None], 0.0, loss)
        loss_ref[...] = jnp.broadcast_to(loss, loss_ref.shape)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _bwd_kernel(z_ref, lab_ref, lse_ref, g_ref, dz_ref, *, block_c,
                ignore_index):
    c_idx = pl.program_id(1)
    z = z_ref[...].astype(jnp.float32)
    lab = lab_ref[...]
    lse = lse_ref[:, :1]
    g = g_ref[:, :1]
    col = c_idx * block_c + jax.lax.broadcasted_iota(
        jnp.int32, z.shape, 1)
    p = jnp.exp(z - lse)
    onehot = (col == lab[:, None]).astype(jnp.float32)
    dz = (p - onehot) * g
    dz = jnp.where((lab == ignore_index)[:, None], 0.0, dz)
    dz_ref[...] = dz.astype(dz_ref.dtype)


def _pick_block(n: int, cands) -> int:
    for c in cands:
        if n % c == 0:
            return c
    return 0


def _fwd_call(z, lab, ignore_index, interpret):
    n, v = z.shape
    block_r = _pick_block(n, (128, 64, 32, 16, 8))
    block_c = _pick_block(v, (1024, 512, 256, 128))
    num_r, num_c = n // block_r, v // block_c
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_c=block_c, num_c=num_c,
                          ignore_index=ignore_index),
        grid=(num_r, num_c),
        in_specs=[
            pl.BlockSpec((block_r, block_c), _im(lambda i, j: (i, j))),
            pl.BlockSpec((block_r,), _im(lambda i, j: (i,))),
        ],
        out_specs=[
            pl.BlockSpec((block_r, 128), _im(lambda i, j: (i, 0))),
            pl.BlockSpec((block_r, 128), _im(lambda i, j: (i, 0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 128), jnp.float32),
            jax.ShapeDtypeStruct((n, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_r, 128), jnp.float32),
            pltpu.VMEM((block_r, 128), jnp.float32),
            pltpu.VMEM((block_r, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(z, lab)
    return loss[:, 0], lse[:, 0]


def _bwd_call(z, lab, lse, g, ignore_index, interpret):
    n, v = z.shape
    block_r = _pick_block(n, (128, 64, 32, 16, 8))
    block_c = _pick_block(v, (1024, 512, 256, 128))
    lse_r = jnp.broadcast_to(lse[:, None], (n, 128))
    g_r = jnp.broadcast_to(g.astype(jnp.float32)[:, None], (n, 128))
    dz = pl.pallas_call(
        functools.partial(_bwd_kernel, block_c=block_c,
                          ignore_index=ignore_index),
        grid=(n // block_r, v // block_c),
        in_specs=[
            pl.BlockSpec((block_r, block_c), _im(lambda i, j: (i, j))),
            pl.BlockSpec((block_r,), _im(lambda i, j: (i,))),
            pl.BlockSpec((block_r, 128), _im(lambda i, j: (i, 0))),
            pl.BlockSpec((block_r, 128), _im(lambda i, j: (i, 0))),
        ],
        out_specs=pl.BlockSpec((block_r, block_c), _im(lambda i, j: (i, j))),
        out_shape=jax.ShapeDtypeStruct((n, v), z.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(z, lab, lse_r, g_r)
    return dz


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _sxent(z, lab, ignore_index, interpret):
    loss, _ = _fwd_call(z, lab, ignore_index, interpret)
    return loss


def _sxent_fwd(z, lab, ignore_index, interpret):
    loss, lse = _fwd_call(z, lab, ignore_index, interpret)
    return loss, (z, lab, lse)


def _sxent_bwd(ignore_index, interpret, res, g):
    z, lab, lse = res
    dz = _bwd_call(z, lab, lse, g, ignore_index, interpret)
    return dz, None


_sxent.defvjp(_sxent_fwd, _sxent_bwd)


def softmax_xent(logits, labels, ignore_index: int = -100,
                 interpret: bool | None = None):
    """Fused per-token softmax cross-entropy loss over the last axis.

    logits [..., V]; labels int [...] (a trailing size-1 axis is
    squeezed).  Returns per-token loss with logits' leading shape, in
    logits' dtype.  Raises NotImplementedError for geometry the kernel
    can't tile even after padding (caller falls back to XLA).
    """
    v = logits.shape[-1]
    lead = logits.shape[:-1]
    if labels.ndim == logits.ndim:
        labels = jnp.squeeze(labels, -1)
    if labels.shape != lead:
        raise NotImplementedError(
            f"softmax_xent: labels {labels.shape} vs logits lead {lead}")
    if interpret is None:
        interpret = _interpret_default()
    z = logits.reshape(-1, v)
    lab = labels.reshape(-1).astype(jnp.int32)
    n = z.shape[0]
    if n == 0:
        return jnp.zeros(lead, logits.dtype)
    # pad the vocab to a lane multiple with -1e30 (exp underflows to 0)
    # and rows to a sublane multiple with ignore_index rows (loss 0)
    vp = -(-v // 128) * 128
    np_ = -(-n // 8) * 8
    if vp != v:
        z = jnp.pad(z, ((0, 0), (0, vp - v)), constant_values=_NEG_INF)
    if np_ != n:
        z = jnp.pad(z, ((0, np_ - n), (0, 0)))
        lab = jnp.pad(lab, (0, np_ - n), constant_values=ignore_index)
    loss = _sxent(z, lab, int(ignore_index), interpret)
    return loss[:n].reshape(lead).astype(logits.dtype)
