"""Optimizers.

Reference parity: python/paddle/optimizer/* and fluid/optimizer.py:58 (the
Optimizer base: minimize = backward + apply_gradients; 15 optimizers) plus
the per-op C++ kernels (operators/optimizers/adam_op.cc, momentum_op.cc,
lamb_op.cc, lars_momentum_op.cc ...).

TPU-native: each optimizer is ONE pure update rule
    _update(param, grad, slots, lr, t) -> (new_param, new_slots)
used two ways:
  * eagerly by `step()` (dygraph UX: grads read off `.grad`),
  * functionally by `apply_pytree()` inside a jitted/pjit'd train step, where
    `slots` live in an explicit opt-state pytree (and can carry ZeRO-style
    PartitionSpecs — see paddle_tpu.distributed.sharding).
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import no_grad
from ..nn.clip import ClipGradBase
from ..nn.layer_base import Parameter
from ..tensor import Tensor
from . import lr as lr_mod
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._weight_decay = self._parse_wd(weight_decay)
        # per-parameter slot storage keyed by id(param)
        self._slots: dict[int, dict[str, Any]] = {}
        self._step_count = 0

    @staticmethod
    def _parse_wd(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        if callable(weight_decay):
            # paddle.regularizer.L1Decay/L2Decay — a grad transform
            return weight_decay
        return float(getattr(weight_decay, "_regularization_coeff",
                             getattr(weight_decay, "coeff", 0.0)))

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        return self._learning_rate if isinstance(self._learning_rate, LRScheduler) \
            else None

    # -- update rule (override) -------------------------------------------
    def _slot_names(self) -> list[str]:
        return []

    def _init_slot(self, name: str, p_val) -> Any:
        return jnp.zeros_like(p_val)

    def _update(self, p, g, slots: dict, lr, t):
        """Pure. p/g jax arrays, slots dict of arrays, lr scalar, t step."""
        raise NotImplementedError

    # -- eager path --------------------------------------------------------
    def _get_slots(self, p: Parameter) -> dict:
        key = id(p)
        if key not in self._slots:
            self._slots[key] = {n: self._init_slot(n, p.value)
                                for n in self._slot_names()}
        return self._slots[key]

    @no_grad()
    def step(self):
        self._step_count += 1
        params = self._parameter_list or []
        params_grads = [(p, p.grad) for p in params
                        if p.grad is not None and not p.stop_gradient]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        base_lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            lr = base_lr * p.optimize_attr.get("learning_rate", 1.0) \
                if hasattr(p, "optimize_attr") else base_lr
            slots = self._get_slots(p)
            g_val = g.value.astype(p.dtype) if g.dtype != p.dtype else g.value
            g_val = self._apply_decay(p.value, g_val)
            new_p, new_slots = self._update(p.value, g_val, slots, lr,
                                            self._step_count)
            p._value = new_p
            self._slots[id(p)] = new_slots

    def _apply_decay(self, p_val, g_val):
        """Coupled decay (fluid regularizer semantics); AdamW overrides.
        A callable regularizer (L1Decay/L2Decay) transforms the grad."""
        wd = self._weight_decay
        if callable(wd):
            return wd(p_val, g_val)
        if wd:
            return g_val + wd * p_val
        return g_val

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.program import Variable, collect_params

        if isinstance(loss, Variable):
            # static-graph capture: register the train objective on the
            # current main Program; Executor.run performs the jitted
            # value_and_grad + update (static/program.py train_step)
            from ..static import default_main_program

            prog = default_main_program()
            prog._train = (loss, self)
            if not self._parameter_list:
                self._parameter_list = collect_params([loss])
            return None, []
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in (self._parameter_list or [])]

    # -- functional path (jit/pjit train steps) ----------------------------
    def init_pytree(self, params: dict):
        """Opt-state pytree for a {name: array} param dict."""
        return {
            name: {n: self._init_slot(n, v) for n in self._slot_names()}
            for name, v in params.items()
        }

    def apply_pytree(self, params: dict, grads: dict, state: dict,
                     lr=None, step=None):
        """Pure update over {name: array} pytrees. Returns (params, state).
        Call inside jit; lr/step may be traced scalars.

        In-place state contract (the device-resident engine relies on
        it): the returned (params, state) pytrees have EXACTLY the input
        treedefs — same names, same slot keys, same shapes/dtypes leaf
        for leaf.  That is what lets a caller jit the step with
        `donate_argnums` on params/opt-state and have XLA alias every
        input buffer onto its output (a true in-place update, the
        reference's fluid inplace op buffers) instead of allocating a
        fresh copy of the model + slots each step.  `_update`
        implementations therefore must not add, drop, rename, or
        re-dtype slots based on traced values; params without a grad
        pass through as the SAME leaves (aliasing, zero cost)."""
        lr = self.get_lr() if lr is None else lr
        t = (self._step_count + 1) if step is None else step
        if self._grad_clip is not None:
            grads = self._grad_clip.clip_pytree(grads)
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state[name]
                continue
            g = self._apply_decay(p, g.astype(p.dtype))
            new_params[name], new_state[name] = self._update(
                p, g, state[name], lr, t)
            if set(new_state[name]) != set(state[name]):
                raise RuntimeError(
                    f"{type(self).__name__}._update changed opt-state "
                    f"slots for {name!r}: {sorted(state[name])} -> "
                    f"{sorted(new_state[name])}; this breaks buffer "
                    "donation (apply_pytree in-place state contract)")
        return new_params, new_state

    # -- checkpointing ----------------------------------------------------
    def state_dict(self):
        sd = {"step_count": self._step_count}
        params = self._parameter_list or []
        for i, p in enumerate(params):
            if id(p) in self._slots:
                for n, v in self._slots[id(p)].items():
                    sd[f"{p.name or i}__{n}"] = Tensor(v) if not isinstance(v, Tensor) else v
        if self._lr_scheduler is not None:
            sd["LR_Scheduler"] = self._lr_scheduler.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("step_count", 0))
        params = self._parameter_list or []
        for i, p in enumerate(params):
            slots = {}
            for n in self._slot_names():
                key = f"{p.name or i}__{n}"
                if key in state_dict:
                    v = state_dict[key]
                    slots[n] = v.value if isinstance(v, Tensor) else jnp.asarray(v)
            if slots:
                self._slots[id(p)] = slots
        if "LR_Scheduler" in state_dict and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(state_dict["LR_Scheduler"])

    set_dict = set_state_dict


class SGD(Optimizer):
    def _update(self, p, g, slots, lr, t):
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _slot_names(self):
        return ["velocity"]

    def _update(self, p, g, slots, lr, t):
        v = self._momentum * slots["velocity"] + g
        if self._use_nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _slot_names(self):
        return ["moment"]

    def _init_slot(self, name, p_val):
        return jnp.full_like(p_val, self._init_acc)

    def _update(self, p, g, slots, lr, t):
        m = slots["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _slot_names(self):
        return ["moment1", "moment2"]

    def _update(self, p, g, slots, lr, t):
        b1, b2 = self._beta1, self._beta2
        g32 = g.astype(jnp.float32)
        m = b1 * slots["moment1"] + (1 - b1) * g32
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g32)
        t = jnp.asarray(t, jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return (p - upd.astype(p.dtype)), {"moment1": m, "moment2": v}

    def _init_slot(self, name, p_val):
        return jnp.zeros(p_val.shape, jnp.float32)


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        from ..regularizer import L1Decay
        if isinstance(weight_decay, L1Decay):
            raise TypeError(
                "AdamW applies DECOUPLED L2 weight decay; L1Decay has no "
                "decoupled analog here — use paddle.optimizer.Adam with "
                "weight_decay=L1Decay(...) for coupled L1")
        self._wd_coeff = float(weight_decay) if not hasattr(weight_decay, "_regularization_coeff") \
            else float(weight_decay._regularization_coeff)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_decay(self, p_val, g_val):
        return g_val  # decoupled

    def _update(self, p, g, slots, lr, t):
        new_p, new_slots = super()._update(p, g, slots, lr, t)
        # decoupled decay (the adamw flag in optimizers/adam_op.cc)
        wd = self._wd_coeff if self._decay_enabled else 0.0
        new_p = new_p - lr * wd * p
        return new_p, new_slots

    _decay_enabled = True

    def step(self):
        if self._apply_decay_param_fun is None:
            return super().step()
        # per-parameter decay decision: split the param list, run twice
        all_params = self._parameter_list
        decay = [p for p in all_params
                 if self._apply_decay_param_fun(p.name or "")]
        decay_ids = {id(p) for p in decay}
        nodecay = [p for p in all_params if id(p) not in decay_ids]
        try:
            self._parameter_list = decay
            self._decay_enabled = True
            super().step()
            self._step_count -= 1  # counted once for both halves
            self._parameter_list = nodecay
            self._decay_enabled = False
            super().step()
        finally:
            self._parameter_list = all_params
            self._decay_enabled = True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _slot_names(self):
        return ["moment", "inf_norm"]

    def _update(self, p, g, slots, lr, t):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(g))
        t = jnp.asarray(t, jnp.float32)
        new_p = p - (lr / (1 - b1 ** t)) * m / (u + self._epsilon)
        return new_p, {"moment": m, "inf_norm": u}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon

    def _slot_names(self):
        return ["avg_squared_grad", "avg_squared_update"]

    def _update(self, p, g, slots, lr, t):
        rho, eps = self._rho, self._epsilon
        asg = rho * slots["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        upd = g * jnp.sqrt(slots["avg_squared_update"] + eps) / jnp.sqrt(asg + eps)
        asu = rho * slots["avg_squared_update"] + (1 - rho) * jnp.square(upd)
        return p - lr * upd, {"avg_squared_grad": asg, "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _slot_names(self):
        return ["mean_square", "mean_grad", "momentum"]

    def _update(self, p, g, slots, lr, t):
        rho, eps = self._rho, self._epsilon
        ms = rho * slots["mean_square"] + (1 - rho) * jnp.square(g)
        if self._centered:
            mg = rho * slots["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            mg = slots["mean_grad"]
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * slots["momentum"] + lr * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _slot_names(self):
        return ["moment1", "moment2"]

    def _update(self, p, g, slots, lr, t):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        t = jnp.asarray(t, jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._lamb_wd * p
        w_norm = jnp.linalg.norm(p.ravel())
        r_norm = jnp.linalg.norm(r.ravel())
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class LarsMomentum(Optimizer):
    """LARS (optimizers/lars_momentum_op.cc; fluid LarsMomentumOptimizer:1612)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon

    def _slot_names(self):
        return ["velocity"]

    def _update(self, p, g, slots, lr, t):
        w_norm = jnp.linalg.norm(p.ravel())
        g_norm = jnp.linalg.norm(g.ravel())
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm /
            (g_norm + self._lars_wd * w_norm + self._eps), 1.0)
        v = self._momentum * slots["velocity"] + \
            lr * local_lr * (g + self._lars_wd * p)
        return p - v, {"velocity": v}


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _slot_names(self):
        return ["squared", "linear"]

    def _update(self, p, g, slots, lr, t):
        sq_new = slots["squared"] + jnp.square(g)
        lp = -self._lr_power
        sigma = (sq_new ** lp - slots["squared"] ** lp) / lr
        lin = slots["linear"] + g - sigma * p
        quad = sq_new ** lp / lr + 2 * self._l2
        pre = jnp.sign(lin) * self._l1 - lin
        new_p = jnp.where(jnp.abs(lin) > self._l1, pre / quad, 0.0)
        return new_p, {"squared": sq_new, "linear": lin}


class Dpsgd(SGD):
    """Differentially-private SGD (optimizers/dpsgd_op.cc) — noise added to
    grads; simplified gaussian mechanism."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16,
                 sigma=1.0, parameters=None, **kw):
        super().__init__(learning_rate, parameters)
        self._clip, self._batch, self._sigma = clip, batch_size, sigma

    def _update(self, p, g, slots, lr, t):
        from ..framework import random as _random

        gn = jnp.linalg.norm(g.ravel())
        g = g / jnp.maximum(1.0, gn / self._clip)
        noise = jax.random.normal(_random.split_key(), g.shape, jnp.float32) \
            * self._sigma * self._clip / self._batch
        return p - lr * (g + noise.astype(g.dtype)), slots


# fluid-era name aliases (fluid.optimizer.*Optimizer)
SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum
FtrlOptimizer = Ftrl
DpsgdOptimizer = Dpsgd

from .lr import *  # noqa: F401,F403,E402
from . import lr  # noqa: F401,E402
from .wrappers import (ModelAverage, ExponentialMovingAverage,  # noqa: E402
                       EMA, LookaheadOptimizer)  # noqa: F401
