"""`paddle.reader` — reader (generator-creator) combinators.

Reference parity: python/paddle/reader/decorator.py (map_readers:91,
shuffle:133, chain:182, compose:247, buffered:307, firstn:366,
xmap_readers:411, multiprocess_reader:504, cache:51).  A "reader" here
is a zero-arg callable returning an iterator of samples; every
combinator returns a new reader and is lazy until called.

These are host-side data plumbing, deliberately independent of jax —
the modern path is paddle_tpu.io.DataLoader, but the fluid-era example
scripts compose pipelines with these.
"""
from __future__ import annotations

import itertools
import multiprocessing
import queue as _queue
import random as _random
import threading

__all__ = [
    "cache", "map_readers", "buffered", "compose", "chain", "shuffle",
    "firstn", "xmap_readers", "multiprocess_reader", "ComposeNotAligned",
]


def cache(reader):
    """Materialize `reader`'s output once; replay from memory thereafter."""
    all_data = tuple(reader())

    def cached():
        yield from all_data

    return cached


def map_readers(func, *readers):
    """Yield func(s1, s2, ...) over samples zipped from each reader."""

    def mapped():
        its = [r() for r in readers]
        yield from map(func, *its)

    return mapped


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of `buf_size` samples.

    Draws from the framework RNG chain (paddle.seed reproduces the
    order) rather than the global `random` module.
    """

    epoch = itertools.count()

    def shuffled():
        from ..framework import random as _fr
        # per-epoch stream: reproducible after paddle.seed(), but each
        # pass over the reader shuffles differently (the reference's
        # global random.shuffle likewise advances across epochs)
        rng = _random.Random(f"{_fr.get_seed()}:{next(epoch)}")
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    """Concatenate readers end-to-end."""

    def chained():
        yield from itertools.chain(*(r() for r in readers))

    return chained


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into combined samples: reader A yielding (1, 2) and
    reader B yielding 3 compose to (1, 2, 3).  With check_alignment
    (default True), readers of unequal length raise ComposeNotAligned."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        its = [r() for r in readers]
        if check_alignment:
            _missing = object()
            for outputs in itertools.zip_longest(*its, fillvalue=_missing):
                # identity test, NOT `in`: tuple membership uses == which
                # numpy array samples evaluate elementwise
                if any(o is _missing for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*its):
                yield sum((make_tuple(o) for o in outputs), ())

    return composed


def buffered(reader, size):
    """Read ahead into a bounded buffer on a daemon thread (overlaps
    producer IO with consumer compute)."""
    _end = object()

    def buffered_reader():
        q = _queue.Queue(maxsize=size)
        exc = []

        def fill():
            try:
                for sample in reader():
                    q.put(sample)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                exc.append(e)
            finally:
                q.put(_end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            sample = q.get()
            if sample is _end:
                break
            yield sample
        if exc:
            raise exc[0]

    return buffered_reader


def firstn(reader, n):
    """Limit the reader to its first `n` samples."""

    def firstn_reader():
        yield from itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map `mapper` over samples with `process_num` worker threads.

    With order=True, output order matches input order (workers tag each
    sample with its index; a reorder stage releases them sequentially).
    Threads, not processes: mappers are typically IO/numpy decode work
    that releases the GIL; this also keeps jax-importing parents safe
    (no fork of a live backend).
    """
    _end = object()

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        exc = []

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:  # noqa: BLE001
                exc.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(_end)

        def work():
            while True:
                item = in_q.get()
                if item is _end:
                    out_q.put(_end)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as e:  # noqa: BLE001
                    exc.append(e)
                    out_q.put(_end)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished, pending, next_idx = 0, {}, 0
        while finished < process_num:
            item = out_q.get()
            if item is _end:
                finished += 1
                continue
            i, mapped = item
            if not order:
                yield mapped
            else:
                pending[i] = mapped
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if order:  # drain any stragglers in index order
            for i in sorted(pending):
                yield pending[i]
        if exc:
            raise exc[0]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave several readers, each running in its own process.

    Samples are pickled through a multiprocessing.Queue (the `use_pipe`
    flag is accepted for signature parity; both modes use the queue —
    the reference's pipe mode is a ujson-over-pipe serialization detail,
    not a semantic difference).
    """
    if len(readers) < 1:
        raise ValueError("multiprocess_reader needs at least one reader")
    _end = "__reader_end__"

    def _worker(r, q):
        try:
            for sample in r():
                q.put(sample)
        finally:
            q.put(_end)

    def mp_reader():
        ctx = multiprocessing.get_context("spawn")  # fork-unsafe under jax
        q = ctx.Queue(queue_size)
        procs = [ctx.Process(target=_worker, args=(r, q), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        try:
            while finished < len(readers):
                sample = q.get()
                if isinstance(sample, str) and sample == _end:
                    finished += 1
                    continue
                yield sample
        finally:
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    return mp_reader
