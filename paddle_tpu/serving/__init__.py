"""paddle_tpu.serving — adaptive-batching TPU serving engine.

The runtime layer between the AOT Predictor (paddle_tpu.inference, the
AnalysisPredictor parity surface) and "heavy traffic": concurrent
requests are coalesced into padded fixed-shape batches drawn from a
finite bucket grid, every bucket is AOT-warmed at startup so
steady-state serving never compiles, and a dependency-free HTTP front
end exposes /predict, /healthz, and Prometheus /metrics with graceful
SIGTERM drain.

    from paddle_tpu import serving
    engine = serving.ServingEngine("export/model",
                                   buckets="1,2,4,8x64,128")
    with serving.ServingServer(engine, port=8866) as srv:
        srv.wait()          # until SIGTERM → drain → clean exit

Autoregressive traffic runs through the continuous-batching
GenerationEngine instead (serving/generation.py): prefill seeds a
device-resident KV cache, ONE donated decode executable advances every
in-flight sequence a token per iteration, and the scheduler
admits/retires requests at iteration boundaries.  Mounted on the same
HTTP server as streaming POST /generate:

    gen = serving.GenerationEngine(model, max_slots=8)
    with serving.ServingServer(None, gen_engine=gen, port=8866) as srv:
        srv.wait()

or one-shot from the high-level API: ``paddle.Model(net).serve(...)`` /
``.serve_generate(...)``.
"""
from .engine import (BucketSpec, DeadlineExceededError, EngineStoppedError,
                     QueueFullError, ServingEngine)
from .metrics import GenerationMetrics, RouterMetrics, ServingMetrics

__all__ = ["ServingEngine", "ServingServer", "ServingClient", "BucketSpec",
           "ServingMetrics", "GenerationMetrics", "RouterMetrics",
           "GenerationEngine", "GenerationHandle", "CacheGeometry",
           "SlotScheduler", "PrefixCache", "FleetRouter", "QueueFullError",
           "DeadlineExceededError", "EngineStoppedError"]


def __getattr__(name):  # lazy: keeps `python -m paddle_tpu.serving.server`
    if name == "ServingServer":     # / .client runnable without runpy's
        from .server import ServingServer   # double-import warning
        return ServingServer
    if name == "ServingClient":
        from .client import ServingClient
        return ServingClient
    if name in ("GenerationEngine", "GenerationHandle"):
        from . import generation
        return getattr(generation, name)
    if name == "CacheGeometry":
        from .kv_cache import CacheGeometry
        return CacheGeometry
    if name == "SlotScheduler":
        from .scheduler import SlotScheduler
        return SlotScheduler
    if name == "PrefixCache":
        from .prefix_cache import PrefixCache
        return PrefixCache
    if name == "FleetRouter":
        from .router import FleetRouter
        return FleetRouter
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
