"""Minimal stdlib client for the serving HTTP API.

`ServingClient` wraps /predict, /generate (blocking and token-streaming
SSE), /healthz, and /metrics with urllib.request (no dependencies —
usable from any host that can reach the server).  The __main__ entry is
the load generator tools/serve_smoke.sh drives: N requests from K
threads — pure /predict, pure streaming /generate, or a mixed blend —
then a one-line JSON summary on stdout (with client-side TTFT and
inter-token quantiles for generation traffic).  `--mixed-wave L:S@LL,SL`
interleaves long and short prompts at a fixed ratio and reports
per-class percentiles — the one-flag probe for "does chunked prefill
hold short streams' inter-token p99 while a long prompt streams in".

Tracing: when the process tracer is enabled (FLAGS_trace_sample_rate >
0) every predict/generate starts a client-side root span and sends its
W3C `traceparent` header, so the server's queue/prefill/decode spans
join the caller's trace; the head-sampling decision is derived from the
trace_id, so client and server agree without coordination.  Pass
`traceparent=` explicitly to join an existing trace instead; the header
actually sent is kept on `client.last_traceparent`.
"""
from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from ..monitor import tracing as _tracing

__all__ = ["ServingClient", "ServingHTTPError"]


class ServingHTTPError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class ServingClient:
    def __init__(self, url: str, timeout: float = 30.0, tracer=None,
                 retries: int = 0, retry_backoff_s: float = 0.05):
        """`retries` > 0 turns on client-side retry of IDEMPOTENT
        non-streaming requests (predict, blocking generate, GETs): a
        connection reset or replica 5xx is retried up to `retries` times
        with jittered exponential backoff, and a 429's `Retry-After`
        header is honored as the wait.  504 (deadline) is never retried
        — the deadline is just as blown on attempt two.  Streaming
        generate is NOT retried here: mid-stream resume is the router's
        job (journaled failover), not the client's.  Default 0 keeps the
        historical raise-on-first-failure behavior."""
        self.base = url.rstrip("/")
        self.timeout = timeout
        self._tracer = tracer
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.last_traceparent = None  # header sent on the last request
        self._tls = threading.local()  # per-thread attempt accounting

    @property
    def last_attempts(self) -> int:
        """Attempts the calling thread's last request took (>=1)."""
        return getattr(self._tls, "attempts", 1)

    @property
    def tracer(self):
        return self._tracer if self._tracer is not None \
            else _tracing.default_tracer()

    def _start_span(self, name: str, traceparent, attrs=None):
        """(span, header) for one outgoing request: an explicit
        `traceparent=` is forwarded as-is (the caller owns that span);
        otherwise a client root span supplies the header."""
        if traceparent is not None:
            self.last_traceparent = traceparent
            return None, traceparent
        tracer = self.tracer
        if not tracer.enabled:
            self.last_traceparent = None
            return None, None
        span = tracer.start_span(name, attrs=attrs)
        self.last_traceparent = span.traceparent
        return span, span.traceparent

    def _retry_delay(self, attempt: int, retry_after=None) -> float:
        if retry_after is not None:
            try:
                return float(retry_after) * (1.0 + 0.1 * random.random())
            except (TypeError, ValueError):
                pass
        return self.retry_backoff_s * attempt * (0.5 + random.random())

    def _request(self, path: str, body=None, traceparent=None):
        headers = {"Content-Type": "application/json"}
        if traceparent:
            headers["traceparent"] = traceparent
        data = json.dumps(body).encode() if body is not None else None
        attempt = 0
        while True:
            attempt += 1
            self._tls.attempts = attempt
            req = urllib.request.Request(
                self.base + path, data=data, headers=headers,
                method="POST" if body is not None else "GET")
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as r:
                    return r.status, r.read()
            except urllib.error.HTTPError as e:  # non-2xx carries a body
                raw = e.read()
                # 429 waits out Retry-After; transient 5xx backs off;
                # 504 means the deadline is gone either way
                retryable = e.code == 429 or (e.code >= 500
                                              and e.code != 504)
                if attempt <= self.retries and retryable:
                    time.sleep(self._retry_delay(
                        attempt, e.headers.get("Retry-After")
                        if e.code == 429 else None))
                    continue
                return e.code, raw
            except OSError:  # connection reset/refused (URLError too)
                if attempt <= self.retries:
                    time.sleep(self._retry_delay(attempt))
                    continue
                raise

    def predict(self, inputs, dtypes=None, deadline_ms=None,
                traceparent=None):
        """inputs: list of single-sample arrays/nested lists (no batch
        dim).  Returns list of numpy outputs; raises ServingHTTPError on
        backpressure (429), draining (503), deadline (504)."""
        body = {"inputs": [np.asarray(x).tolist() for x in inputs]}
        if dtypes:
            body["dtypes"] = [str(d) for d in dtypes]
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        span, header = self._start_span(
            "client.predict", traceparent, attrs={"n_inputs": len(inputs)})
        status, raw = self._request("/predict", body, traceparent=header)
        if span is not None:
            span.set_attr("http_status", status)
            span.end(status="ok" if status == 200 else "error")
        if status != 200:
            # status decides FIRST: a proxy's non-JSON 502/504 body must
            # surface as ServingHTTPError, not a JSONDecodeError
            try:
                detail = json.loads(raw or b"{}").get("error", "?")
            except ValueError:
                detail = (raw or b"").decode(errors="replace")[:200]
            raise ServingHTTPError(status, detail)
        payload = json.loads(raw or b"{}")
        return [np.asarray(o, dtype=np.dtype(dt)) for o, dt in
                zip(payload["outputs"], payload["dtypes"])]

    def _gen_body(self, prompt, max_new_tokens, do_sample, temperature,
                  top_k, seed, eos_token_id, deadline_ms, stream):
        body = {"prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new_tokens),
                "do_sample": bool(do_sample),
                "temperature": float(temperature), "top_k": int(top_k),
                "seed": int(seed), "stream": stream}
        if eos_token_id is not None:
            body["eos_token_id"] = int(eos_token_id)
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        return body

    def generate(self, prompt, max_new_tokens=32, *, do_sample=False,
                 temperature=1.0, top_k=0, seed=0, eos_token_id=None,
                 deadline_ms=None, traceparent=None) -> dict:
        """Blocking generation: {"tokens": [...], "ttft_ms",
        "latency_ms"}.  Raises ServingHTTPError on 429/503/504."""
        span, header = self._start_span(
            "client.generate", traceparent,
            attrs={"prompt_len": len(prompt),
                   "max_new_tokens": int(max_new_tokens)})
        status, raw = self._request("/generate", self._gen_body(
            prompt, max_new_tokens, do_sample, temperature, top_k, seed,
            eos_token_id, deadline_ms, stream=False), traceparent=header)
        if span is not None:
            span.set_attr("http_status", status)
            span.end(status="ok" if status == 200 else "error")
        if status != 200:
            try:
                detail = json.loads(raw or b"{}").get("error", "?")
            except ValueError:
                detail = (raw or b"").decode(errors="replace")[:200]
            raise ServingHTTPError(status, detail)
        return json.loads(raw or b"{}")

    def generate_stream(self, prompt, max_new_tokens=32, *,
                        do_sample=False, temperature=1.0, top_k=0, seed=0,
                        eos_token_id=None, deadline_ms=None,
                        traceparent=None):
        """Streaming generation: yields one event dict per SSE frame as
        the server's decode loop produces it — {"token": t} per decoded
        token, then a final {"done": true, "tokens": n, ...} (which
        carries "error" when the request failed mid-decode).  Admission
        failures (429/503) raise ServingHTTPError before the first
        yield."""
        span, header = self._start_span(
            "client.generate_stream", traceparent,
            attrs={"prompt_len": len(prompt),
                   "max_new_tokens": int(max_new_tokens)})
        self._tls.attempts = 1  # streaming never client-retries
        headers = {"Content-Type": "application/json"}
        if header:
            headers["traceparent"] = header
        req = urllib.request.Request(
            self.base + "/generate",
            data=json.dumps(self._gen_body(
                prompt, max_new_tokens, do_sample, temperature, top_k,
                seed, eos_token_id, deadline_ms, stream=True)).encode(),
            headers=headers, method="POST")
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read() or b"{}").get("error", "?")
            except ValueError:
                detail = "?"
            if span is not None:
                span.set_attr("http_status", e.code)
                span.end(status="error")
            raise ServingHTTPError(e.code, detail) from None
        ntok = 0
        try:
            with resp:
                for line in resp:  # urllib undoes the chunked framing
                    line = line.strip()
                    if not line.startswith(b"data: "):
                        continue
                    evt = json.loads(line[len(b"data: "):])
                    if "token" in evt:
                        ntok += 1
                        if span is not None and ntok == 1:
                            span.event("first_token")
                    yield evt
                    if evt.get("done"):
                        return
        finally:
            if span is not None:
                span.set_attr("tokens", ntok)
                span.end()

    def healthz(self) -> dict:
        status, raw = self._request("/healthz")
        return {"status_code": status, **json.loads(raw or b"{}")}

    def metrics(self) -> str:
        status, raw = self._request("/metrics")
        if status != 200:
            raise ServingHTTPError(status, raw.decode(errors="replace"))
        return raw.decode()


def main(argv=None):
    import argparse
    import threading

    parser = argparse.ArgumentParser(description="serving load generator")
    parser.add_argument("--url", required=True)
    parser.add_argument("--requests", type=int, default=20)
    parser.add_argument("--concurrency", type=int, default=4)
    parser.add_argument("--mode", default="predict",
                        choices=("predict", "generate", "mixed"),
                        help="traffic blend: /predict, streaming "
                             "/generate, or alternating both")
    parser.add_argument("--shape", default="8",
                        help="comma-separated SAMPLE shape, e.g. '16' or "
                             "'16,8' (no batch dim) — predict traffic")
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--prompt-len", type=int, default=8,
                        help="generate traffic: prompt token count")
    parser.add_argument("--max-new", type=int, default=16,
                        help="generate traffic: max_new_tokens")
    parser.add_argument("--vocab", type=int, default=200,
                        help="generate traffic: prompt id upper bound")
    parser.add_argument("--sample", action="store_true",
                        help="generate traffic: temperature/top-k "
                             "sampling instead of greedy")
    parser.add_argument("--shared-prefix-len", type=int, default=0,
                        help="generate traffic: every prompt starts with "
                             "the SAME fixed-seed token prefix of this "
                             "length (exercises the server's prefix "
                             "cache), followed by a random suffix")
    parser.add_argument("--mixed-wave", default=None, metavar="L:S@LL,SL",
                        help="generate traffic: mix of long and short "
                             "prompts — 'L:S@LL,SL' sends L long (LL "
                             "tokens) per S short (SL tokens) prompts, "
                             "e.g. '1:4@48,8', and the summary reports "
                             "per-class ttft/inter-token percentiles "
                             "(the chunked-prefill p99 claim in one "
                             "flag); overrides --prompt-len")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--retries", type=int, default=0,
                        help="client-side retries for idempotent "
                             "non-streaming requests (connection reset, "
                             "replica 5xx, Retry-After on 429)")
    args = parser.parse_args(argv)

    wave = None
    if args.mixed_wave:
        try:
            ratio, lens = args.mixed_wave.split("@")
            n_long, n_short = (int(x) for x in ratio.split(":"))
            len_long, len_short = (int(x) for x in lens.split(","))
            if min(n_long, n_short, len_long, len_short) < 1 \
                    or len_long <= len_short:
                raise ValueError
        except ValueError:
            parser.error("--mixed-wave must be 'L:S@LONGLEN,SHORTLEN' "
                         "with LONGLEN > SHORTLEN >= 1, e.g. '1:4@48,8'")
        wave = (n_long, n_short, len_long, len_short)

    shared_prefix = []
    if args.shared_prefix_len > 0:
        if args.shared_prefix_len >= args.prompt_len:
            parser.error("--shared-prefix-len must be < --prompt-len "
                         "(at least one random suffix token)")
        shared_prefix = [int(t) for t in np.random.RandomState(1234)
                         .randint(1, args.vocab, args.shared_prefix_len)]

    shape = tuple(int(d) for d in args.shape.split(",") if d.strip())
    client = ServingClient(args.url, retries=args.retries)
    results = {"ok": 0, "backpressure": 0, "errors": 0}
    attempts: list[int] = []
    ttfts, gaps = {"all": []}, {"all": []}
    if wave:
        for cls in ("long", "short"):
            ttfts[cls], gaps[cls] = [], []
    gen_tokens = [0]
    lock = threading.Lock()

    def predict_once(rs):
        x = (rs.randint(0, 100, shape) if "int" in args.dtype
             else rs.randn(*shape)).astype(args.dtype)
        client.predict([x])

    def wave_class(i: int) -> str:
        """Deterministic long/short interleave: the first `n_long` of
        every (n_long + n_short)-request cycle are long."""
        n_long, n_short = wave[0], wave[1]
        return "long" if i % (n_long + n_short) < n_long else "short"

    def generate_once(rs, cls=None):
        plen = args.prompt_len if cls is None \
            else (wave[2] if cls == "long" else wave[3])
        n_rand = plen - len(shared_prefix)
        prompt = shared_prefix + [int(t) for t in rs.randint(1, args.vocab,
                                                             n_rand)]
        t0 = last = time.perf_counter()
        ntok = 0
        my_ttft, my_gaps, err = None, [], None
        for evt in client.generate_stream(
                prompt, args.max_new, do_sample=args.sample,
                temperature=0.8, top_k=5,
                seed=int(rs.randint(1 << 30))):
            now = time.perf_counter()
            if "token" in evt:
                ntok += 1
                if my_ttft is None:
                    my_ttft = now - t0
                else:
                    my_gaps.append(now - last)
                last = now
            if evt.get("done"):
                err = evt.get("error")
        with lock:
            gen_tokens[0] += ntok
            for k in ("all",) + ((cls,) if cls else ()):
                if my_ttft is not None:
                    ttfts[k].append(my_ttft * 1e3)
                gaps[k].extend(g * 1e3 for g in my_gaps)
        if err:
            raise ServingHTTPError(200, err)

    def worker(wid: int, n: int):
        rs = np.random.RandomState(args.seed + wid)
        for i in range(n):
            gen = (args.mode == "generate"
                   or (args.mode == "mixed" and (wid + i) % 2 == 0))
            cls = wave_class(wid + i) if (wave and gen) else None
            try:
                if gen:
                    generate_once(rs, cls)
                else:
                    predict_once(rs)
                key = "ok"
            except ServingHTTPError as e:
                key = "backpressure" if e.status == 429 else "errors"
            except Exception:  # noqa: BLE001
                key = "errors"
            with lock:
                results[key] += 1
                attempts.append(client.last_attempts)

    per = [args.requests // args.concurrency] * args.concurrency
    for i in range(args.requests % args.concurrency):
        per[i] += 1
    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i, n))
               for i, n in enumerate(per) if n]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results["elapsed_s"] = round(time.perf_counter() - t0, 3)
    results["client_qps"] = round(results["ok"] /
                                  max(results["elapsed_s"], 1e-9), 1)
    if attempts:
        # attempts-per-request percentiles: >1 means the fleet made the
        # client work for its answer (retried resets / Retry-After)
        results["attempts_p50"] = round(
            float(np.percentile(attempts, 50)), 2)
        results["attempts_p99"] = round(
            float(np.percentile(attempts, 99)), 2)
        results["attempts_max"] = int(max(attempts))
    if args.mode in ("generate", "mixed"):
        results["gen_tokens"] = gen_tokens[0]
        results["client_tokens_per_sec"] = round(
            gen_tokens[0] / max(results["elapsed_s"], 1e-9), 1)

        def pct(xs, q):
            return round(float(np.percentile(xs, q)), 3) if xs else None

        results["ttft_p50_ms"] = pct(ttfts["all"], 50)
        results["inter_token_p50_ms"] = pct(gaps["all"], 50)
        results["inter_token_p99_ms"] = pct(gaps["all"], 99)
        if wave:
            # per-class percentiles: the chunked-prefill claim is that
            # SHORT streams' inter-token p99 stays flat while LONG
            # prompts prefill — per-class is the only way to see it
            for cls in ("long", "short"):
                results[f"{cls}_ttft_p50_ms"] = pct(ttfts[cls], 50)
                results[f"{cls}_ttft_p99_ms"] = pct(ttfts[cls], 99)
                results[f"{cls}_inter_token_p50_ms"] = pct(gaps[cls], 50)
                results[f"{cls}_inter_token_p99_ms"] = pct(gaps[cls], 99)
    print(json.dumps(results), flush=True)
    return 0 if results["errors"] == 0 else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
