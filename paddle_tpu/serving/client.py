"""Minimal stdlib client for the serving HTTP API.

`ServingClient` wraps /predict, /healthz, and /metrics with
urllib.request (no dependencies — usable from any host that can reach
the server).  The __main__ entry is the load generator
tools/serve_smoke.sh drives: N requests from K threads, then a one-line
JSON summary on stdout.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np

__all__ = ["ServingClient", "ServingHTTPError"]


class ServingHTTPError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class ServingClient:
    def __init__(self, url: str, timeout: float = 30.0):
        self.base = url.rstrip("/")
        self.timeout = timeout

    def _request(self, path: str, body=None):
        req = urllib.request.Request(
            self.base + path,
            data=(json.dumps(body).encode() if body is not None else None),
            headers={"Content-Type": "application/json"},
            method="POST" if body is not None else "GET")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:  # non-2xx still carries a body
            return e.code, e.read()

    def predict(self, inputs, dtypes=None, deadline_ms=None):
        """inputs: list of single-sample arrays/nested lists (no batch
        dim).  Returns list of numpy outputs; raises ServingHTTPError on
        backpressure (429), draining (503), deadline (504)."""
        body = {"inputs": [np.asarray(x).tolist() for x in inputs]}
        if dtypes:
            body["dtypes"] = [str(d) for d in dtypes]
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        status, raw = self._request("/predict", body)
        if status != 200:
            # status decides FIRST: a proxy's non-JSON 502/504 body must
            # surface as ServingHTTPError, not a JSONDecodeError
            try:
                detail = json.loads(raw or b"{}").get("error", "?")
            except ValueError:
                detail = (raw or b"").decode(errors="replace")[:200]
            raise ServingHTTPError(status, detail)
        payload = json.loads(raw or b"{}")
        return [np.asarray(o, dtype=np.dtype(dt)) for o, dt in
                zip(payload["outputs"], payload["dtypes"])]

    def healthz(self) -> dict:
        status, raw = self._request("/healthz")
        return {"status_code": status, **json.loads(raw or b"{}")}

    def metrics(self) -> str:
        status, raw = self._request("/metrics")
        if status != 200:
            raise ServingHTTPError(status, raw.decode(errors="replace"))
        return raw.decode()


def main(argv=None):
    import argparse
    import threading

    parser = argparse.ArgumentParser(description="serving load generator")
    parser.add_argument("--url", required=True)
    parser.add_argument("--requests", type=int, default=20)
    parser.add_argument("--concurrency", type=int, default=4)
    parser.add_argument("--shape", default="8",
                        help="comma-separated SAMPLE shape, e.g. '16' or "
                             "'16,8' (no batch dim)")
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    shape = tuple(int(d) for d in args.shape.split(",") if d.strip())
    client = ServingClient(args.url)
    results = {"ok": 0, "backpressure": 0, "errors": 0}
    lock = threading.Lock()

    def worker(wid: int, n: int):
        rs = np.random.RandomState(args.seed + wid)
        for _ in range(n):
            x = (rs.randint(0, 100, shape) if "int" in args.dtype
                 else rs.randn(*shape)).astype(args.dtype)
            try:
                client.predict([x])
                key = "ok"
            except ServingHTTPError as e:
                key = "backpressure" if e.status == 429 else "errors"
            except Exception:  # noqa: BLE001
                key = "errors"
            with lock:
                results[key] += 1

    per = [args.requests // args.concurrency] * args.concurrency
    for i in range(args.requests % args.concurrency):
        per[i] += 1
    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i, n))
               for i, n in enumerate(per) if n]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results["elapsed_s"] = round(time.perf_counter() - t0, 3)
    results["client_qps"] = round(results["ok"] /
                                  max(results["elapsed_s"], 1e-9), 1)
    print(json.dumps(results), flush=True)
    return 0 if results["errors"] == 0 else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
