"""Adaptive-batching serving engine over the AOT Predictor.

The reference's inference stack stops at single-process, single-request
``AnalysisPredictor::Run`` (api/analysis_predictor.cc:306); this module
is the layer it never had: concurrent requests land on a bounded queue,
a batcher thread coalesces them into padded fixed-shape batches drawn
from a finite bucket grid (batch × sequence), and one AOT-compiled
callable per bucket amortizes across users — continuous batching in the
Clipper/Orca sense, shaped for XLA (recompile storms are the TPU failure
mode, so every bucket is warmed at startup and steady-state serving
never compiles).

Contracts:
  * per-request ``concurrent.futures.Future`` — deadline expiry and
    cancellation drop a request *before* it wastes a batch slot
  * bounded queue — ``submit`` raises :class:`QueueFullError` instead of
    buffering unboundedly (backpressure is the client's signal to shed)
  * padding is invisible — batch slots are padded with zeros and peeled
    off row-wise; a padded sequence dim is sliced back to the request's
    original length.  Responses are bitwise-identical to a direct
    single-request ``Predictor.run`` (tested).
  * graceful drain — ``drain()`` rejects new work, flushes everything
    queued, and completes every in-flight future (the SIGTERM path in
    serving/server.py reuses distributed/resilience.py's latch pattern)
  * chaos hooks — each dispatched batch passes through
    ``utils.chaos.on_step``, so crash/preempt/slow injection exercises
    the serving recovery paths exactly like the training runtime's
"""
from __future__ import annotations

import concurrent.futures
import logging
import queue
import threading
import time

import numpy as np

from ..framework import flags as _flags
from ..framework.transfer import host_fetch
from ..monitor import tracing as _tracing
from ..utils import chaos
from ..utils.profiler import RecordEvent
from .metrics import ServingMetrics

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["BucketSpec", "ServingEngine", "QueueFullError",
           "DeadlineExceededError", "EngineStoppedError"]


class QueueFullError(RuntimeError):
    """Backpressure: the request queue is at capacity — shed or retry."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before a batch could serve it."""


class EngineStoppedError(RuntimeError):
    """submit() after drain()/stop() — the engine no longer accepts work."""


class BucketSpec:
    """Finite shape-bucket grid: batch sizes × optional sequence lengths.

    String form (``FLAGS_serving_buckets``): ``"1,2,4,8"`` (batch only)
    or ``"1,2,4,8x16,32,64"`` (batch × sequence).  A request is padded UP
    to the smallest bucket that fits; oversized requests are rejected at
    submit.  Keeping the grid finite is what makes warmup exhaustive and
    steady-state serving compile-free.
    """

    def __init__(self, batch_sizes, seq_lens=None):
        self.batch_sizes = sorted(set(int(b) for b in batch_sizes))
        if not self.batch_sizes or self.batch_sizes[0] < 1:
            raise ValueError(f"invalid batch buckets {batch_sizes!r}")
        self.seq_lens = (sorted(set(int(s) for s in seq_lens))
                         if seq_lens else None)
        if self.seq_lens and self.seq_lens[0] < 1:
            raise ValueError(f"invalid seq buckets {seq_lens!r}")

    @classmethod
    def parse(cls, spec: str) -> "BucketSpec":
        spec = (spec or "").strip()
        if not spec:
            raise ValueError("empty bucket spec")
        batch_part, _, seq_part = spec.partition("x")
        batches = [int(s) for s in batch_part.split(",") if s.strip()]
        seqs = [int(s) for s in seq_part.split(",") if s.strip()] \
            if seq_part else None
        return cls(batches, seqs)

    @classmethod
    def powers_of_two(cls, max_batch: int, seq_lens=None) -> "BucketSpec":
        sizes, b = [], 1
        while b < max_batch:
            sizes.append(b)
            b *= 2
        sizes.append(int(max_batch))
        return cls(sizes, seq_lens)

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    def batch_for(self, n: int) -> int:
        for b in self.batch_sizes:
            if b >= n:
                return b
        return self.max_batch

    def seq_for(self, s: int):
        if self.seq_lens is None:
            return s
        for q in self.seq_lens:
            if q >= s:
                return q
        raise ValueError(f"sequence length {s} exceeds the largest bucket "
                         f"{self.seq_lens[-1]}")

    def __repr__(self):
        seq = ",".join(map(str, self.seq_lens)) if self.seq_lens else "-"
        return (f"BucketSpec(batch={','.join(map(str, self.batch_sizes))}, "
                f"seq={seq})")


class _Request:
    __slots__ = ("inputs", "orig_lens", "key", "future", "t_enqueue",
                 "deadline", "span", "own_span", "span_queue", "span_exec")

    def __init__(self, inputs, orig_lens, key, deadline, span=None,
                 own_span=False):
        self.inputs = inputs
        self.orig_lens = orig_lens     # per-input pre-pad seq length
        self.key = key                 # padded shape signature = bucket
        self.future = concurrent.futures.Future()
        self.t_enqueue = time.monotonic()
        self.deadline = deadline       # absolute monotonic time or None
        self.span = span               # request span (tracing), or None
        self.own_span = own_span       # engine-rooted: engine ends it
        self.span_queue = None         # live "serve.queued" child
        self.span_exec = None          # live "serve.execute" child

    def end_spans(self, status: str):
        """Terminal span cleanup for early exits (deadline, cancel,
        drain): close any live child, and the root if the engine owns
        it (server-owned roots are ended by the HTTP handler)."""
        for s in (self.span_queue, self.span_exec):
            if s is not None:
                s.end(status=status)
        self.span_queue = self.span_exec = None
        if self.span is not None:
            self.span.set_attr("status", status)
            if self.own_span:
                self.span.end()
            self.span = None


_WAKE = object()   # queue sentinel: wakes an idle-blocked batcher


def _as_predictor(model):
    """Accept a Predictor, an export prefix/Config, or an in-memory Layer;
    anything else with a .run(list)->list method is used as-is (test
    seam)."""
    from .. import inference
    from ..nn.layer_base import Layer

    if isinstance(model, Layer):
        return inference.Predictor.from_layer(model)
    if isinstance(model, (str, inference.Config)):
        return inference.create_predictor(
            model if isinstance(model, inference.Config)
            else inference.Config(model))
    if hasattr(model, "run"):
        return model
    raise TypeError(f"cannot serve a {type(model).__name__}; pass a "
                    "Predictor, export prefix, Config, or nn.Layer")


class ServingEngine:
    """Coalesces concurrent requests into padded fixed-shape batches.

    Args:
      model: Predictor | export path prefix | inference.Config | nn.Layer.
      max_batch_size / batch_timeout_ms / queue_depth: adaptive-batcher
        knobs; default from ``FLAGS_serving_max_batch`` /
        ``FLAGS_serving_timeout_ms`` / ``FLAGS_serving_queue_depth``.
      buckets: BucketSpec or its string form (``FLAGS_serving_buckets``);
        default = powers of two up to max_batch_size, no seq bucketing.
      seq_axis: per-sample axis padded to the sequence bucket (batch axis
        excluded — requests are single samples).
      pad_value: fill for padded slots/positions.
      input_specs: [(shape, dtype), ...] *with* the batch dim (e.g.
        ``[(-1, 128), "int32")]``) used for warmup; defaults to the
        predictor's export manifest.

    Lifecycle: ``start()`` warms every bucket (so serving never
    compiles), ``submit()``/``predict()`` serve, ``drain()`` finishes
    in-flight work and rejects new requests, ``stop()`` kills the
    batcher.  Usable as a context manager.
    """

    def __init__(self, model, *, max_batch_size=None, batch_timeout_ms=None,
                 queue_depth=None, buckets=None, seq_axis=0, pad_value=0,
                 input_specs=None, warmup=True, unpad_outputs=True,
                 max_buckets=32):
        self._predictor = _as_predictor(model)
        max_batch_size = int(max_batch_size
                             or _flags.flag("FLAGS_serving_max_batch", 8))
        if batch_timeout_ms is None:
            batch_timeout_ms = float(
                _flags.flag("FLAGS_serving_timeout_ms", 5.0))
        if buckets is None:
            buckets = _flags.flag("FLAGS_serving_buckets", "") or None
        if isinstance(buckets, str):
            buckets = BucketSpec.parse(buckets)
        self.buckets = buckets or BucketSpec.powers_of_two(max_batch_size)
        self.batch_timeout_s = max(0.0, batch_timeout_ms / 1e3)
        self.queue_depth = int(queue_depth
                               or _flags.flag("FLAGS_serving_queue_depth",
                                              256))
        self.seq_axis = int(seq_axis)
        self.pad_value = pad_value
        self.unpad_outputs = unpad_outputs
        # Hard cap on DISTINCT shape signatures ever admitted: without
        # input specs there is no submit-time shape validation, and each
        # new signature costs one XLA compile cached forever — untrusted
        # traffic cycling shapes must hit a ValueError, not a compile
        # storm with unbounded executable memory.
        self.max_buckets = int(max_buckets)
        self._seen_keys: set = set()
        self._warmup = warmup
        self._input_specs = self._resolve_specs(input_specs)

        self.metrics = ServingMetrics()
        self._queue: queue.Queue[_Request] = queue.Queue(self.queue_depth)
        self._pending: dict[tuple, list[_Request]] = {}
        self._thread = None
        self._started = False
        self._draining = False
        self._stopped = False
        self._idle = threading.Event()   # queue + pending empty
        self._idle.set()
        self._batch_seq = 0

    # -- setup -------------------------------------------------------------
    def _resolve_specs(self, input_specs):
        if input_specs is None:
            input_specs = getattr(self._predictor, "_input_specs", None)
            if input_specs is not None:
                input_specs = [(tuple(s["shape"]), s["dtype"])
                               for s in input_specs]
            return input_specs
        out = []
        for s in input_specs:
            if isinstance(s, (tuple, list)) and len(s) == 2 \
                    and not np.isscalar(s[0]):
                shape, dtype = s
            else:  # InputSpec-like
                shape, dtype = s.shape, s.dtype
            from ..framework.dtype import convert_dtype
            out.append((tuple(int(d) if d is not None else -1
                              for d in shape), convert_dtype(dtype)))
        return out

    def start(self) -> "ServingEngine":
        if self._started:
            return self
        if self._warmup:
            self.warm()
        self._started = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle-serving-batcher")
        self._thread.start()
        return self

    def warm(self):
        """AOT-warm every (batch × seq) bucket so steady-state serving
        never compiles.  No-op without input specs (a Layer-backed engine
        then compiles lazily, once per bucket, on first traffic)."""
        if not self._input_specs:
            logger.warning("serving warmup skipped: no input specs "
                           "(pass input_specs= to pre-compile buckets)")
            return 0
        seqs = self.buckets.seq_lens or [None]
        seen = set()
        warmed = 0
        for b in self.buckets.batch_sizes:
            for s in seqs:
                arrays = []
                ok = True
                for shape, dtype in self._input_specs:
                    sample = list(shape[1:])
                    if s is not None and len(sample) > self.seq_axis:
                        if sample[self.seq_axis] in (-1, s):
                            sample[self.seq_axis] = s
                    if any(d < 0 for d in sample):
                        ok = False  # non-seq dynamic dim: cannot warm
                        break
                    arrays.append(np.zeros([b] + sample,
                                           np.dtype(dtype)))
                if not ok:
                    logger.warning("serving warmup skipped for bucket "
                                   "(%d, %s): unresolved dynamic dim", b, s)
                    continue
                key = tuple((a.shape, str(a.dtype)) for a in arrays)
                if key in seen:   # fixed seq dim: several seq buckets
                    continue      # resolve to one shape — warm it once
                seen.add(key)
                # per-request signatures drop the batch dim
                self._seen_keys.add(tuple(
                    ((a.shape[1:]), str(a.dtype)) for a in arrays))
                with RecordEvent("paddle.serve/warmup"):
                    self._predictor.run(arrays)
                warmed += 1
        self._sync_compile_count()
        logger.info("serving warmup compiled %d bucket(s): %s", warmed,
                    self.buckets)
        return warmed

    def _sync_compile_count(self):
        n = getattr(self._predictor, "compile_count", None)
        if n is not None:
            self.metrics.set_compile_count(n)

    # -- request intake ----------------------------------------------------
    def _prepare(self, inputs):
        """Single-sample arrays → (padded arrays, orig seq lens, group
        key).  The group key is the padded per-sample signature — one key
        == one XLA bucket."""
        # intake converts host payloads (lists / client numpy), never
        # device buffers; the device round-trip copies on distribution
        arrays = [np.asarray(x) for x in inputs]  # noqa: PTA001
        if self._input_specs:
            if len(arrays) != len(self._input_specs):
                raise ValueError(
                    f"expected {len(self._input_specs)} inputs, got "
                    f"{len(arrays)}")
            for j, (a, (shape, _dt)) in enumerate(
                    zip(arrays, self._input_specs)):
                sample = shape[1:]  # requests carry no batch dim
                if a.ndim != len(sample):
                    raise ValueError(
                        f"inputs[{j}] has rank {a.ndim}, expected rank "
                        f"{len(sample)} (sample shape {list(sample)})")
                for k, d in enumerate(sample):
                    if d > 0 and a.shape[k] != d:
                        # a short seq may pad UP to a FIXED export dim,
                        # but only when the bucket it lands in IS that
                        # dim — any other bucket is a shape the artifact
                        # cannot serve and warm() never compiled
                        if (k == self.seq_axis
                                and self.buckets.seq_lens is not None
                                and a.shape[k] < d
                                and self.buckets.seq_for(a.shape[k]) == d):
                            continue
                        raise ValueError(
                            f"inputs[{j}] dim {k} is {a.shape[k]}, "
                            f"expected {d}")
        padded, orig = [], []
        for a in arrays:
            orig.append(a.shape[self.seq_axis]
                        if a.ndim > self.seq_axis else None)
            if self.buckets.seq_lens is not None \
                    and a.ndim > self.seq_axis:
                want = self.buckets.seq_for(a.shape[self.seq_axis])
                if want != a.shape[self.seq_axis]:
                    pad = [(0, 0)] * a.ndim
                    pad[self.seq_axis] = (0, want - a.shape[self.seq_axis])
                    a = np.pad(a, pad, constant_values=self.pad_value)
            padded.append(a)
        key = tuple((a.shape, str(a.dtype)) for a in padded)
        if key not in self._seen_keys:
            if len(self._seen_keys) >= self.max_buckets:
                raise ValueError(
                    f"shape signature {key} would exceed max_buckets="
                    f"{self.max_buckets} distinct serving shapes — fix "
                    "the client, pass input_specs for validation, or "
                    "raise max_buckets")
            self._seen_keys.add(key)
        return padded, orig, key

    def submit(self, inputs, deadline_ms=None, span=None) \
            -> concurrent.futures.Future:
        """Enqueue one request (a list of single-sample arrays, NO batch
        dim).  Returns a Future resolving to the per-request output list.
        Raises QueueFullError under backpressure and EngineStoppedError
        once draining/stopped.

        `span=` joins the request to a caller-owned trace span (the HTTP
        layer passes its server span); without one, a direct API caller
        gets a head-sampled engine root span."""
        if self._draining or self._stopped:
            self.metrics.count("rejected_draining")
            raise EngineStoppedError("serving engine is draining — no new "
                                     "requests accepted")
        if not self._started:
            raise EngineStoppedError("serving engine not started — call "
                                     "start()")
        padded, orig, key = self._prepare(
            inputs if isinstance(inputs, (list, tuple)) else [inputs])
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        own_span = False
        if span is None:
            tracer = _tracing.default_tracer()
            if tracer.enabled:
                span = tracer.start_span("serve.request")
                own_span = True
        if span is not None and not span.sampled:
            span, own_span = None, False
        req = _Request(padded, orig, key, deadline, span=span,
                       own_span=own_span)
        if span is not None:
            # child spans MUST attach before enqueue: the batcher may
            # claim the request the instant it lands on the queue
            req.span_queue = span.child("serve.queued")
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            req.end_spans("rejected_queue_full")
            self.metrics.count("rejected_queue_full")
            raise QueueFullError(
                f"serving queue at capacity ({self.queue_depth}); retry "
                "with backoff") from None
        self._idle.clear()
        self.metrics.count("accepted")
        return req.future

    def predict(self, inputs, timeout=None, deadline_ms=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(inputs, deadline_ms=deadline_ms).result(timeout)

    # -- the batcher loop --------------------------------------------------
    def _wake(self):
        """Nudge a batcher blocked on an empty queue (drain/stop path).
        A full queue is by definition non-empty — the batcher is awake."""
        try:
            self._queue.put_nowait(_WAKE)
        except queue.Full:
            pass

    def _run(self):
        tick = max(5e-4, min(self.batch_timeout_s / 4.0, 0.005)) \
            if self.batch_timeout_s else 5e-4
        while True:
            # idle (nothing pending, not shutting down): block with NO
            # timeout — zero wakeups under zero traffic.  The tick poll
            # only runs while a partial batch awaits its flush deadline.
            block = (not self._pending
                     and not (self._draining or self._stopped))
            try:
                req = self._queue.get(timeout=None if block else tick)
            except queue.Empty:
                req = None
            if req is not None:
                if req is not _WAKE:
                    self._route(req)
                while True:  # drain whatever else arrived this tick
                    try:
                        r2 = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if r2 is not _WAKE:
                        self._route(r2)
            self._sweep_deadlines()
            now = time.monotonic()
            for key in list(self._pending):
                lst = self._pending[key]
                while len(lst) >= self.buckets.max_batch:
                    self._dispatch(key, lst[:self.buckets.max_batch])
                    del lst[:self.buckets.max_batch]
                if lst and (self._draining or self._stopped
                            or now - lst[0].t_enqueue
                            >= self.batch_timeout_s):
                    self._dispatch(key, lst)
                    lst.clear()
                if not lst:
                    del self._pending[key]
            if not self._pending and self._queue.empty():
                self._idle.set()
                if self._draining or self._stopped:
                    return

    def _route(self, req: _Request):
        self._pending.setdefault(req.key, []).append(req)

    def _sweep_deadlines(self):
        now = time.monotonic()
        for lst in self._pending.values():
            keep = []
            for r in lst:
                if r.future.done():   # client-side cancel: just drop it
                    self.metrics.count("cancelled")
                    r.end_spans("cancelled")
                elif r.deadline is not None and now > r.deadline:
                    self.metrics.count("deadline_expired")
                    r.end_spans("deadline_expired")
                    r.future.set_exception(DeadlineExceededError(
                        "request deadline passed while queued"))
                else:
                    keep.append(r)
            lst[:] = keep

    def _dispatch(self, key, reqs):
        # claim futures; a cancelled request never occupies a slot
        live = []
        for r in reqs:
            if r.future.set_running_or_notify_cancel():
                live.append(r)
            else:
                self.metrics.count("cancelled")
                r.end_spans("cancelled")
        if not live:
            return
        self._batch_seq += 1
        now = time.monotonic()
        for r in live:
            self.metrics.observe_queue_wait(now - r.t_enqueue)
            # queued → dispatched transition (host timestamps only —
            # this is the engine's hot path)
            if r.span_queue is not None:
                r.span_queue.end()
                r.span_queue = None
            if r.span is not None:
                r.span_exec = r.span.child("serve.execute",
                                           batch=len(live),
                                           batch_seq=self._batch_seq)
        try:
            chaos.on_step(self._batch_seq)  # fault injection seam
            bucket_b = self.buckets.batch_for(len(live))
            arrays = []
            for j in range(len(live[0].inputs)):
                rows = np.stack([r.inputs[j] for r in live])
                if bucket_b > len(live):
                    fill = np.full((bucket_b - len(live),) + rows.shape[1:],
                                   self.pad_value, rows.dtype)
                    rows = np.concatenate([rows, fill], axis=0)
                arrays.append(rows)
            with RecordEvent("paddle.serve/batch"):
                outs = self._predictor.run(arrays)
        except Exception as e:  # noqa: BLE001 - fail THIS batch, keep serving
            self.metrics.count("errors", len(live))
            logger.exception("serving batch %d failed", self._batch_seq)
            for r in live:
                r.end_spans("error")
                if not r.future.done():
                    r.future.set_exception(e)
            return
        try:
            # waste accounting in elements: padded batch slots AND padded
            # sequence positions both count against the ratio
            total_elems = sum(int(a.size) for a in arrays)
            real_elems = 0
            for r in live:
                for j, a in enumerate(r.inputs):
                    e = int(a.size)
                    orig = r.orig_lens[j]
                    if orig is not None and a.ndim > self.seq_axis \
                            and a.shape[self.seq_axis]:
                        e = e * orig // a.shape[self.seq_axis]
                    real_elems += e
            self.metrics.observe_batch(len(live), bucket_b, real_elems,
                                       total_elems)
            self._sync_compile_count()
            done_t = time.monotonic()
            # Result distribution is the batcher's one sanctioned
            # device→host point (PTA005), and the rows handed to client
            # futures must OWN their bytes (PTA001): a zero-copy view of
            # the batch output would pin the whole [bucket_b, ...] buffer
            # per request and alias storage the runtime may reuse for the
            # next dispatched batch.
            with host_fetch():
                host_outs = [np.array(o, copy=True) for o in outs]
            for i, r in enumerate(live):
                row = [self._unpad(o[i], r) for o in host_outs]
                # stop() may have failed this future while the batch was
                # on the accelerator — a done future is not re-resolved
                if not r.future.done():
                    r.future.set_result(row)
                    self.metrics.observe_completion(done_t - r.t_enqueue)
                r.end_spans("ok")
        except Exception as e:  # noqa: BLE001 - e.g. an output without the
            # batch dim: fail this batch's unresolved futures, never the
            # batcher thread (the engine's single point of failure)
            logger.exception("serving batch %d result distribution failed",
                             self._batch_seq)
            for r in live:
                r.end_spans("error")
                if not r.future.done():
                    self.metrics.count("errors")
                    r.future.set_exception(e)

    def _unpad(self, out, req: _Request):
        """Slice a padded sequence dim back to the request's original
        length.  Only fires when seq bucketing actually padded, on
        outputs of at least the padded input's rank that carry the
        padded dim at seq_axis (a lower-rank pooled output — e.g. class
        logits whose size happens to equal the bucket — is never
        sliced).  Set ``unpad_outputs=False`` for models whose outputs
        don't follow the input's sequence layout."""
        if self.buckets.seq_lens is None or not self.unpad_outputs:
            return out
        for j, orig in enumerate(req.orig_lens):
            if orig is None:
                continue
            padded = req.inputs[j].shape[self.seq_axis]
            if padded != orig and out.ndim >= req.inputs[j].ndim \
                    and out.ndim > self.seq_axis \
                    and out.shape[self.seq_axis] == padded:
                sl = [slice(None)] * out.ndim
                sl[self.seq_axis] = slice(0, orig)
                return out[tuple(sl)]
        return out

    # -- shutdown ----------------------------------------------------------
    def drain(self, timeout=None) -> bool:
        """Graceful: reject new work, flush every queued request, wait
        for all in-flight futures, stop the batcher.  Returns True when
        fully drained."""
        self._draining = True
        if self._thread is None:
            return True
        self._wake()
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        drained = self._idle.wait(timeout)
        # one budget for the WHOLE drain: join only gets what wait left
        self._thread.join(None if deadline is None
                          else max(0.0, deadline - time.monotonic()))
        alive = self._thread.is_alive()
        if not alive:
            self._thread = None
        # a submit racing the drain flag can slip one request into the
        # queue after the batcher's final empty-check — fail it rather
        # than leaving its future pending forever
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is _WAKE:
                continue
            drained = False
            if not req.future.done():
                req.future.set_exception(EngineStoppedError(
                    "request arrived during drain"))
        return drained and not alive

    def stop(self):
        """Hard stop: fail everything still queued, stop the batcher."""
        self._stopped = True
        self._draining = True
        thread = self._thread
        batcher_alive = False
        if thread is not None:
            self._wake()
            thread.join(5.0)
            batcher_alive = thread.is_alive()
            if not batcher_alive:
                self._thread = None
        # the queue is thread-safe — always safe to fail leftovers (the
        # done() guards make a benign race with the batcher harmless)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is _WAKE:
                continue
            if not req.future.done():
                req.future.set_exception(
                    EngineStoppedError("engine stopped"))
        if batcher_alive:
            # a batch is still on the accelerator: _pending belongs to
            # the batcher thread — touching it here would race its own
            # mutations.  It sees _stopped when the batch returns,
            # flushes what's left, and exits.
            logger.warning("stop(): batcher still executing a batch; its "
                           "remaining requests resolve when it returns")
            return
        for lst in self._pending.values():
            for r in lst:
                if not r.future.done():
                    r.future.set_exception(
                        EngineStoppedError("engine stopped"))
        self._pending.clear()

    @property
    def draining(self) -> bool:
        return self._draining

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        if exc[0] is None:
            self.drain(timeout=30.0)
        self.stop()
        return False
