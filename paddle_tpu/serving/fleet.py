"""Serving fleet supervisor: spawn N generation replicas, watch them,
respawn the dead, account the downtime.

`distributed/elastic.py` supervises TRAINING ranks with
shrink-and-continue; this is the same supervisor shape pointed at the
serving fleet, where the contract is different: a lost replica is not a
membership shrink to ride out but CAPACITY to restore.  The supervisor

  * hosts the PR-16 `PodCoordinator` — each replica process registers
    its URL under ``serving/replica/<rank>/url`` and heartbeats
    (serving/generation.py main() does both when PADDLE_POD_COORD is
    set), and the fleet router subscribes to the same coordinator
    (``--coord``) so replica death reaches the router as an EPOCH DELTA,
    not a probe timeout;
  * watches process exits (a SIGKILLed replica is declared dead the next
    poll) and heartbeats (a silent-but-serving replica — the
    PADDLE_CHAOS_REPLICA_PARTITION drill — is fenced with SIGKILL so it
    cannot keep answering requests the router thinks it lost);
  * respawns dead replicas with jittered backoff
    (`FLAGS_fleet_respawn_backoff_s`): delete the corpse's URL key,
    spawn a fresh process under the SAME rank, wait for the new
    registration, then `mark_live` — which bumps the epoch so the router
    re-admits the replacement on the same delta channel it saw the
    death;
  * accounts every death→respawned gap: a flight-recorder dump with
    reason ``replica_lost`` carrying the CUMULATIVE ``down_s`` (later
    dumps overwrite earlier ones per path+mtime, so the running total is
    what the goodput ledger must see), which `distributed/goodput.py`
    ingests into the `down` badput bucket — serving downtime lands in
    the same ledger as training downtime.

Parse-friendly stdout lines (tools/serve_smoke.sh greps them):

    paddle_tpu.serving.fleet coord <host:port>
    paddle_tpu.serving.fleet replica <rank> up at <url>
    paddle_tpu.serving.fleet replica <rank> lost (<reason>)
    paddle_tpu.serving.fleet replica <rank> respawned at <url> down=<s>s
    fleet drain clean
"""
from __future__ import annotations

import logging
import os
import random
import signal
import subprocess
import sys
import threading
import time

from ..distributed.podcoord import (DEAD_EXIT, DEAD_PARTITION,
                                    PodCoordinator, PodClient)
from ..distributed.resilience import PreemptionGuard
from ..framework import flags as _flags
from ..monitor import flightrec
from ..utils.metrics import default_registry

logger = logging.getLogger("paddle_tpu.serving.fleet")

__all__ = ["ReplicaSupervisor", "LOST_REASONS"]

LOST_REASONS = (DEAD_EXIT, "heartbeat_timeout", DEAD_PARTITION, "drain")

# per-rank lifecycle states
_UP = "up"                  # process running, URL registered, marked live
_WAIT_URL = "waiting_url"   # process spawned, registration pending
_BACKOFF = "backoff"        # dead; respawn scheduled at respawn_at
_FAILED = "failed"          # respawn budget exhausted; stays down


class ReplicaSupervisor:
    """Own the serving fleet's lifecycle: coordinator + N replica
    processes + the respawn loop.  `cmd` is the full argv of ONE replica
    (typically ``[sys.executable, "-m", "paddle_tpu.serving.generation",
    ...]``); each rank gets PADDLE_POD_COORD/RANK/WORLD on top of
    `env`."""

    def __init__(self, cmd, world, *, env=None, heartbeat_timeout_s=2.0,
                 respawn_backoff_s=None, max_respawns=None,
                 telemetry_dir=None, log_dir=None, registry=None,
                 poll_interval_s=0.05, install_signal_handlers=False):
        self.cmd = list(cmd)
        self.world = int(world)
        self.env = dict(env or {})
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.respawn_backoff_s = float(
            respawn_backoff_s if respawn_backoff_s is not None
            else _flags.flag("FLAGS_fleet_respawn_backoff_s", 0.5))
        self.max_respawns = max_respawns  # None = unlimited
        self.telemetry_dir = telemetry_dir
        self.log_dir = log_dir
        self.poll_interval_s = float(poll_interval_s)
        self._install_signals = install_signal_handlers
        reg = registry if registry is not None else default_registry()
        self._m_lost = reg.counter(
            "paddle_fleet_replica_lost_total",
            "serving replicas lost by the supervisor, by reason",
            label="reason", preset=LOST_REASONS, fixed=True)
        self._m_respawns = reg.counter(
            "paddle_fleet_replica_respawns_total",
            "serving replicas respawned by the supervisor")
        self._g_live = reg.gauge(
            "paddle_fleet_live_replicas",
            "replicas the supervisor believes up and registered")
        self.coord = None
        self._kv = None            # supervisor-side PodClient (rank -1)
        self.procs: list = [None] * self.world
        self._state = [_WAIT_URL] * self.world
        self._respawn_at = [0.0] * self.world
        self._t_dead = [None] * self.world
        self._respawns = [0] * self.world
        self.urls: list = [None] * self.world
        self.downs: list[float] = []   # every death→respawned gap, s
        self._down_total = 0.0
        self._logs = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._drain_done = threading.Event()
        self._drain_clean = True
        self._thread = None
        self._guard = None
        self.draining = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        if self.telemetry_dir:
            flightrec.configure(directory=self.telemetry_dir)
        self.coord = PodCoordinator(
            self.world,
            heartbeat_timeout_s=self.heartbeat_timeout_s).start()
        # rank -1: kv access without joining the membership
        self._kv = PodClient(self.coord.address, rank=-1)
        print(f"paddle_tpu.serving.fleet coord "  # noqa: PTA006 - parse
              f"{self.coord.address}", flush=True)  # contract (smoke greps)
        if self._install_signals:
            self._guard = PreemptionGuard()
            self._guard.__enter__()
        for r in range(self.world):
            self._spawn(r)
        self._thread = threading.Thread(target=self._watch_loop,
                                        daemon=True,
                                        name="paddle-fleet-watch")
        self._thread.start()
        return self

    def _spawn(self, r: int):
        e = dict(os.environ)
        e.update(self.env)
        e.update({"PADDLE_POD_COORD": self.coord.address,
                  "PADDLE_POD_RANK": str(r),
                  "PADDLE_POD_WORLD": str(self.world),
                  "PADDLE_TRAINER_ID": str(r)})
        if self.telemetry_dir:
            e["FLAGS_TELEMETRY_DIR"] = os.path.join(
                os.path.abspath(self.telemetry_dir), f"replica{r}")
        out = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            out = open(os.path.join(
                self.log_dir,
                f"replica{r}.{self._respawns[r]}.log"), "wb")
            self._logs.append(out)
        self.procs[r] = subprocess.Popen(
            self.cmd, env=e, stdout=out or subprocess.DEVNULL,
            stderr=subprocess.STDOUT if out else subprocess.DEVNULL)
        self._state[r] = _WAIT_URL

    def wait_ready(self, timeout_s: float = 120.0) -> bool:
        """Block until every replica has registered (initial bring-up)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if all(s == _UP for s in self._state):
                    return True
            if self._stop.is_set():
                return False
            time.sleep(0.05)
        return False

    def replica_url(self, r: int):
        with self._lock:
            return self.urls[r]

    @property
    def respawn_count(self) -> int:
        return sum(self._respawns)

    # -- the watch loop ----------------------------------------------------
    def _watch_loop(self):
        while not self._stop.is_set():
            if self._guard is not None and self._guard.preempted:
                logger.warning("signal %s latched — draining fleet",
                               self._guard.signum)
                self.shutdown()
                return
            self._poll_once()
            time.sleep(self.poll_interval_s)

    def _poll_once(self):
        now = time.monotonic()
        # 1. process exits
        for r in range(self.world):
            if self._state[r] in (_BACKOFF, _FAILED):
                continue
            p = self.procs[r]
            if p is not None and p.poll() is not None:
                self._on_death(r, DEAD_EXIT, now)
        # 2. heartbeat silence: fence alive-but-silent replicas (the
        #    partition drill) so they cannot keep serving after eviction
        for r, why in self.coord.check_heartbeats().items():
            if self._state[r] in (_BACKOFF, _FAILED):
                continue
            p = self.procs[r]
            if p is not None and p.poll() is None:
                p.kill()
                self._on_death(r, DEAD_PARTITION, now)
            else:
                self._on_death(r, why, now)
        # 3. due respawns
        for r in range(self.world):
            if self._state[r] == _BACKOFF and now >= self._respawn_at[r]:
                self._m_respawns.inc()
                self._respawns[r] += 1
                logger.info("fleet: respawning replica %d (attempt %d)",
                            r, self._respawns[r])
                self._spawn(r)
        # 4. pending registrations
        for r in range(self.world):
            if self._state[r] != _WAIT_URL:
                continue
            try:
                raw = self._kv.kv_get(f"serving/replica/{r}/url",
                                      timeout_s=0.05)
            except (OSError, TimeoutError, RuntimeError):
                continue
            if not raw:
                continue
            url = raw.decode("utf-8")
            with self._lock:
                self.urls[r] = url
                self._state[r] = _UP
            if self._t_dead[r] is not None:
                gap = now - self._t_dead[r]
                self._t_dead[r] = None
                with self._lock:
                    self.downs.append(gap)
                    self._down_total += gap
                # re-admit on the router's epoch channel only AFTER the
                # new URL is registered — a revive before registration
                # would hand the router the corpse's stale URL
                self.coord.mark_live(r)
                flightrec.dump("replica_lost", extra={
                    "accounting": {"down_s": round(self._down_total, 3)},
                    "fleet": {"downs": [round(d, 3)
                                        for d in self.downs],
                              "respawns": self.respawn_count}})
                print(f"paddle_tpu.serving.fleet replica {r} "  # noqa: PTA006
                      f"respawned at {url} down={gap:.3f}s",
                      flush=True)  # parse contract (smoke greps)
            else:
                print(f"paddle_tpu.serving.fleet replica {r} "  # noqa: PTA006
                      f"up at {url}", flush=True)  # parse contract
            self._update_live()

    def _on_death(self, r: int, reason: str, now: float):
        if self.draining:
            return
        self._m_lost.inc(reason)
        if self._t_dead[r] is None:
            self._t_dead[r] = now
        # drop the corpse's registration NOW so the eventual respawn's
        # kv_get cannot match the old URL
        try:
            self._kv.kv_delete(f"serving/replica/{r}/url")
        except (OSError, RuntimeError):
            pass
        self.coord.mark_dead(r, reason)
        # the death dump: the goodput ledger sees the outage even if the
        # supervisor dies before the respawn completes
        flightrec.dump("replica_lost", extra={
            "accounting": {"down_s": round(self._down_total, 3)},
            "fleet": {"lost_rank": r, "reason": reason}})
        print(f"paddle_tpu.serving.fleet replica {r} lost "  # noqa: PTA006
              f"({reason})", flush=True)  # parse contract (smoke greps)
        if self.max_respawns is not None \
                and self._respawns[r] >= self.max_respawns:
            logger.error("fleet: replica %d exhausted its %d respawns — "
                         "staying down", r, self.max_respawns)
            self._state[r] = _FAILED
            self._update_live()
            return
        backoff = self.respawn_backoff_s * (0.5 + random.random())
        self._respawn_at[r] = now + backoff
        self._state[r] = _BACKOFF
        logger.warning("fleet: replica %d lost (%s) — respawn in %.2fs",
                       r, reason, backoff)
        self._update_live()

    def _update_live(self):
        self._g_live.set(sum(1 for s in self._state if s == _UP))

    # -- shutdown ----------------------------------------------------------
    def shutdown(self, timeout_s: float = 15.0) -> bool:
        """Drain: SIGTERM every replica (they latch-drain and exit 0),
        wait, then close the coordinator.  Idempotent; True = every
        supervised replica exited cleanly."""
        with self._lock:
            if self.draining:
                # another caller owns the drain: wait for it to finish
                already = True
            else:
                self.draining = True
                already = False
        if already:
            self._drain_done.wait(timeout_s + 10.0)
            return self._drain_clean
        self._stop.set()
        if self._thread is not None \
                and threading.current_thread() is not self._thread:
            self._thread.join(5.0)
        clean = True
        for r, p in enumerate(self.procs):
            if p is None or p.poll() is not None:
                continue
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + timeout_s
        for p in self.procs:
            if p is None:
                continue
            left = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                clean = False
        if self.coord is not None:
            self.coord.close()
        for f in self._logs:
            try:
                f.close()
            except OSError:
                pass
        if self._guard is not None:
            self._guard.__exit__(None, None, None)
            self._guard = None
        print("fleet drain %s"  # noqa: PTA006 - parse contract (smoke greps)
              % ("clean" if clean else "TIMED OUT"), flush=True)
        self._drain_clean = clean
        self._drain_done.set()
        return clean

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(
        description="paddle_tpu serving fleet supervisor: coordinator + "
                    "N replica processes with respawn-on-death",
        usage="python -m paddle_tpu.serving.fleet --world N [opts] -- "
              "<replica argv...>")
    parser.add_argument("--world", type=int, required=True)
    parser.add_argument("--heartbeat-timeout", type=float, default=2.0)
    parser.add_argument("--backoff", type=float, default=None,
                        help="respawn backoff base seconds (default: "
                             "FLAGS_fleet_respawn_backoff_s)")
    parser.add_argument("--max-respawns", type=int, default=None)
    parser.add_argument("--telemetry-dir", default=None)
    parser.add_argument("--log-dir", default=None)
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="replica argv after --")
    args = parser.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("need a replica command after --")

    logging.basicConfig(level=logging.INFO)
    sup = ReplicaSupervisor(
        cmd, args.world, heartbeat_timeout_s=args.heartbeat_timeout,
        respawn_backoff_s=args.backoff, max_respawns=args.max_respawns,
        telemetry_dir=args.telemetry_dir, log_dir=args.log_dir,
        install_signal_handlers=True).start()
    if not sup.wait_ready():
        logger.error("fleet bring-up timed out")
        sup.shutdown()
        return 1
    print(f"paddle_tpu.serving.fleet supervising {args.world} replicas",
          flush=True)
    # run until a latched signal drains us (the watch thread handles it)
    try:
        while not sup._stop.wait(0.2):
            pass
    except KeyboardInterrupt:
        pass
    sup.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
