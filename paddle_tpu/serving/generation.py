"""Continuous-batching autoregressive generation engine.

The serving-side counterpart of the training engine's donation
discipline: the reference stack served autoregressive traffic through
fused_multi_transformer's CacheKV decode behind AnalysisPredictor's
per-request generation loop; this module is that path rebuilt for XLA's
shape discipline, in the Orca iteration-level-scheduling shape:

  * **prefill/decode split** — each admitted prompt runs ONE prefill
    (compiled per prompt-length bucket through the same AOT machinery as
    the Predictor's shape buckets) that seeds its slot's rows of the
    device-resident KV cache; then a single donated, jitted **decode
    step** advances ALL in-flight sequences one token per iteration.
  * **continuous batching** — the scheduler admits queued requests into
    free slots at iteration boundaries (no waiting for the batch to
    drain), retires lanes on EOS/max_new_tokens, and preempts lanes on
    deadline/cancellation; a request admitted mid-decode produces tokens
    bitwise-identical to running alone (tested).
  * **zero steady-state compiles, zero cache round-trips** — every
    executable (decode, release, per-bucket prefill/insert) is AOT
    lowered+compiled at ``start()`` via ``inference.aot_compile``; the
    decode state pytree (serving/kv_cache.py) is donated on every
    transition, so the KV cache lives on device across iterations and
    only the sampled token ids are fetched (under ``host_fetch()``).

Per-slot sampling (greedy / temperature / top-k, per-request seed)
reproduces ``GPTForCausalLM.generate``'s exact PRNG chain — one
``split`` at admission, one per decode iteration — which is what makes
engine output comparable token-for-token with the solo path.
"""
from __future__ import annotations

import collections
import logging
import queue
import threading
import time

import numpy as np

from ..framework import flags as _flags
from ..framework.transfer import host_fetch
from ..monitor import tracing as _tracing
from ..utils import chaos
from ..utils.profiler import RecordEvent
from .engine import (DeadlineExceededError, EngineStoppedError,
                     QueueFullError)
from .kv_cache import (CacheGeometry, admit_slot, make_state, release_slots,
                       state_specs, write_prompt)
from .metrics import GenerationMetrics
from .scheduler import SlotScheduler

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["GenerationEngine", "GenerationHandle"]

_WAKE = object()   # queue sentinel: wakes an idle-blocked decode loop
_END = object()    # handle sentinel: no more tokens


class GenerationHandle:
    """Per-request streaming face: tokens arrive as the decode loop
    produces them; iterate (``for tok in handle``), poll
    (``next_token``), or block for everything (``result``)."""

    def __init__(self, prompt_len: int, max_new_tokens: int):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.tokens: list[int] = []       # appended by the decode thread
        self._q: queue.Queue = queue.Queue()
        self._done = threading.Event()
        self._error: BaseException | None = None
        self._req = None                  # backref set by the engine
        self.t_submit = time.monotonic()
        self.t_first_token = None

    # -- consuming ---------------------------------------------------------
    def next_token(self, timeout=None):
        """Next generated token id, or None when the stream has ended
        (raises the request's error, if it failed)."""
        if self._done.is_set() and self._q.empty():
            if self._error is not None:
                raise self._error
            return None
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"no token within {timeout:g}s") from None
        if item is _END:
            if self._error is not None:
                raise self._error
            return None
        return item

    def __iter__(self):
        while True:
            tok = self.next_token()
            if tok is None:
                return
            yield tok

    def result(self, timeout=None) -> list[int]:
        """Block until the request finishes; the generated token ids
        (prompt excluded).  Raises on deadline expiry / engine failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"generation not finished in {timeout:g}s")
        if self._error is not None:
            raise self._error
        return list(self.tokens)

    # -- control -----------------------------------------------------------
    def cancel(self):
        """Ask the engine to preempt this request at the next iteration
        boundary (its slot is freed; tokens produced so far remain)."""
        req = self._req
        if req is not None:
            req.cancelled = True
            req.engine._wake()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def error(self):
        return self._error

    @property
    def ttft_ms(self):
        if self.t_first_token is None:
            return None
        return (self.t_first_token - self.t_submit) * 1e3

    # -- engine side -------------------------------------------------------
    def _push(self, tok: int):
        if self.t_first_token is None:
            self.t_first_token = time.monotonic()
        self.tokens.append(tok)
        self._q.put(tok)

    def _finish(self, error: BaseException | None = None):
        if self._done.is_set():
            return
        self._error = error
        self._done.set()
        self._q.put(_END)


class _GenRequest:
    __slots__ = ("prompt", "bucket", "max_new_tokens", "do_sample",
                 "temperature", "top_k", "seed", "eos", "deadline",
                 "handle", "engine", "cancelled", "t_last_token",
                 "span", "own_span", "span_queue", "span_decode")

    def __init__(self, engine, prompt, bucket, max_new_tokens, do_sample,
                 temperature, top_k, seed, eos, deadline, span=None,
                 own_span=False):
        self.engine = engine
        self.prompt = prompt               # np.int32 [L]
        self.bucket = bucket               # padded prompt length Sp
        self.max_new_tokens = max_new_tokens
        self.do_sample = do_sample
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.eos = eos                     # int; vocab_size == never
        self.deadline = deadline           # absolute monotonic or None
        self.cancelled = False
        self.t_last_token = None
        self.span = span                   # request span (sampled or None)
        self.own_span = own_span           # engine owns span's end()
        self.span_queue = None             # "gen.queued" child
        self.span_decode = None            # "gen.decode" child
        self.handle = GenerationHandle(len(prompt), max_new_tokens)
        self.handle._req = self

    def end_spans(self, status: str):
        """Close any open child spans and settle the request span with a
        terminal status; the parent is ended here only when the engine
        owns it (direct submit — HTTP requests end theirs upstream)."""
        for s in (self.span_queue, self.span_decode):
            if s is not None:
                s.end(status=status)
        self.span_queue = self.span_decode = None
        if self.span is not None:
            self.span.set_attr("status", status)
            if self.own_span:
                self.span.end()
            self.span = None


class GenerationEngine:
    """Continuous-batching decode over a device-resident KV cache.

    Args:
      model: a causal-LM Layer exposing ``slot_prefill``/``slot_decode``
        (models/gpt.py GPTForCausalLM) and a ``cfg`` with num_layers /
        num_heads / hidden_size / vocab_size / max_position_embeddings.
      max_slots: in-flight sequences per decode iteration
        (``FLAGS_genserve_max_slots``).
      max_seq_len: per-slot cache length S_max >= prompt + new tokens
        (``FLAGS_genserve_max_seq_len``).
      prompt_buckets: admitted prompt-length grid, list or "8,16,32"
        (``FLAGS_genserve_prompt_buckets``); one prefill+insert
        executable pair is AOT-compiled per bucket at start().
      queue_depth: bounded admission queue
        (``FLAGS_genserve_queue_depth``) — ``submit`` raises
        :class:`QueueFullError` beyond it.
      max_top_k: largest per-request top_k accepted (the sampling
        executable carries a static top-k width).

    Lifecycle mirrors ServingEngine: ``start()`` compiles every
    executable (steady state never compiles), ``submit()`` returns a
    streaming :class:`GenerationHandle`, ``drain()`` finishes in-flight
    decodes and rejects new work, ``stop()`` kills the loop.
    """

    def __init__(self, model, *, max_slots=None, max_seq_len=None,
                 prompt_buckets=None, queue_depth=None, max_top_k=64):
        from ..hapi.model import Model as _HapiModel

        if isinstance(model, _HapiModel):
            model = model.network
        for req_attr in ("slot_prefill", "slot_decode", "cfg"):
            if not hasattr(model, req_attr):
                raise TypeError(
                    f"GenerationEngine needs a model with `{req_attr}` "
                    "(a causal LM with the slot-batched KV-cache decode "
                    "path, e.g. models.GPTForCausalLM); got "
                    f"{type(model).__name__}")
        self.model = model
        cfg = model.cfg
        self.max_slots = int(max_slots
                             or _flags.flag("FLAGS_genserve_max_slots", 4))
        self.max_seq_len = int(
            max_seq_len or _flags.flag("FLAGS_genserve_max_seq_len", 256))
        if self.max_seq_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"max_position_embeddings {cfg.max_position_embeddings}")
        if prompt_buckets is None:
            prompt_buckets = _flags.flag("FLAGS_genserve_prompt_buckets",
                                         "16,32,64")
        if isinstance(prompt_buckets, str):
            prompt_buckets = [int(s) for s in prompt_buckets.split(",")
                              if s.strip()]
        self.prompt_buckets = sorted(set(int(b) for b in prompt_buckets))
        if not self.prompt_buckets or self.prompt_buckets[0] < 1:
            raise ValueError(f"invalid prompt buckets {prompt_buckets!r}")
        if self.prompt_buckets[-1] >= self.max_seq_len:
            raise ValueError(
                f"largest prompt bucket {self.prompt_buckets[-1]} leaves "
                f"no room to generate within max_seq_len {self.max_seq_len}")
        self.queue_depth = int(
            queue_depth or _flags.flag("FLAGS_genserve_queue_depth", 128))
        self.max_top_k = int(max_top_k)

        self.geometry = CacheGeometry(
            num_layers=cfg.num_layers, max_slots=self.max_slots,
            max_seq_len=self.max_seq_len, num_heads=cfg.num_heads,
            head_dim=cfg.hidden_size // cfg.num_heads,
            vocab_size=cfg.vocab_size)
        self.metrics = GenerationMetrics(max_slots=self.max_slots)
        self._queue: queue.Queue = queue.Queue(self.queue_depth)
        self._backlog: collections.deque = collections.deque()
        self._sched = SlotScheduler(self.max_slots)
        self._thread = None
        self._started = False
        self._draining = False
        self._stopped = False
        self._idle = threading.Event()
        self._idle.set()
        self._iter = 0
        self.compile_count = 0
        self._state = None
        self._params = None
        self._buffers = None
        self._decode_exec = None
        self._release_exec = None
        self._prefill_execs = {}
        self._insert_execs = {}

    # -- warmup: build + AOT-compile every executable ----------------------
    def start(self) -> "GenerationEngine":
        if self._started:
            return self
        import jax
        import jax.numpy as jnp

        from .. import inference
        from ..nn.layer_base import functional_call, state_pytrees
        from ..tensor import Tensor

        self.model.eval()
        params, buffers = state_pytrees(self.model)
        self._params, self._buffers = params, buffers
        geom = self.geometry
        V = geom.vocab_size
        k_max = min(self.max_top_k, V)
        finfo_min = None  # resolved inside traces

        def sample_token(lg, key, do_sample, temp, top_k):
            """Per-lane sampling, chain-compatible with generate():
            greedy = argmax of raw logits; sampling = temperature scale,
            static-width top-k cutoff (dynamic k), categorical over the
            [1, V] row exactly as the solo path draws it."""
            greedy = jnp.argmax(lg).astype(jnp.int32)
            lg2 = lg / jnp.maximum(temp, 1e-6)
            vals = jax.lax.top_k(lg2, k_max)[0]
            kth = vals[jnp.clip(top_k - 1, 0, k_max - 1)]
            lg3 = jnp.where((top_k > 0) & (lg2 < kth),
                            jnp.finfo(lg2.dtype).min, lg2)
            samp = jax.random.categorical(
                key, lg3[None, :])[0].astype(jnp.int32)
            return jnp.where(do_sample, samp, greedy)

        model, geometry = self.model, geom

        def prefill_step(params, ids, length):
            out, _ = functional_call(
                model, params, (Tensor(ids), length), buffers=buffers,
                mutable=False, method="slot_prefill")
            return out                     # (k [L,Sp,nh,hd], v, logits [V])

        def insert_step(state, slot, k_new, v_new, logits, length, seed,
                        do_sample, temp, top_k, stop_pos, eos):
            state = write_prompt(state, slot, k_new, v_new)
            key, sub = jax.random.split(jax.random.PRNGKey(seed))
            tok1 = sample_token(logits, sub, do_sample, temp, top_k)
            state = admit_slot(state, slot, tok1, length, key, do_sample,
                               temp, top_k, stop_pos, eos)
            return state, tok1

        def decode_step(params, state):
            (logits, kc, vc), _ = functional_call(
                model, params,
                (state["tok"], state["pos"], state["active"],
                 state["k"], state["v"]),
                buffers=buffers, mutable=False, method="slot_decode")
            pair = jax.vmap(jax.random.split)(state["rng"])
            new_keys, subs = pair[:, 0], pair[:, 1]
            toks = jax.vmap(sample_token)(
                logits, subs, state["do_sample"], state["temp"],
                state["top_k"])
            active = state["active"]
            toks = jnp.where(active, toks, state["tok"])
            new_pos = jnp.where(active, state["pos"] + 1, state["pos"])
            finished = active & ((toks == state["eos"])
                                 | (new_pos + 1 >= state["stop_pos"]))
            new_state = dict(state, k=kc, v=vc, tok=toks, pos=new_pos,
                             rng=new_keys, active=active & ~finished)
            return new_state, toks, finished

        def release_step(state, mask):
            return release_slots(state, mask)

        self._state = make_state(geom)
        sspec = state_specs(self._state)
        pspec = inference.spec_tree(params)
        i32 = jax.ShapeDtypeStruct((), np.int32)
        f32 = jax.ShapeDtypeStruct((), np.float32)
        b1 = jax.ShapeDtypeStruct((), np.bool_)
        kv_dt = np.dtype(geometry.dtype)

        with RecordEvent("paddle.genserve/warmup"):
            self._decode_exec = inference.aot_compile(
                decode_step, (pspec, sspec), donate_argnums=(1,))
            self.compile_count += 1
            self._release_exec = inference.aot_compile(
                release_step,
                (sspec, jax.ShapeDtypeStruct((self.max_slots,), np.bool_)),
                donate_argnums=(0,))
            self.compile_count += 1
            for sp in self.prompt_buckets:
                ids = jax.ShapeDtypeStruct((1, sp), np.int32)
                kv = jax.ShapeDtypeStruct(
                    (geom.num_layers, sp, geom.num_heads, geom.head_dim),
                    kv_dt)
                lg = jax.ShapeDtypeStruct((V,), np.float32)
                self._prefill_execs[sp] = inference.aot_compile(
                    prefill_step, (pspec, ids, i32))
                self._insert_execs[sp] = inference.aot_compile(
                    insert_step,
                    (sspec, i32, kv, kv, lg, i32, i32, b1, f32, i32, i32,
                     i32),
                    donate_argnums=(0,))
                self.compile_count += 2
        self.metrics.set_compile_count(self.compile_count)
        logger.info(
            "generation warmup compiled %d executable(s): slots=%d "
            "S_max=%d prompt buckets=%s cache=%.1f MB", self.compile_count,
            self.max_slots, self.max_seq_len, self.prompt_buckets,
            self.geometry.kv_bytes() / 1048576)

        # publish introspection surfaces (monitor/perf.py): the decode
        # op table over /debug/perf, and owner tags so the buffer
        # census attributes the KV cache and weights ("latest engine
        # wins" — one process, one serving engine in practice)
        from ..monitor import perf as _perf

        _perf.register_provider("decode", self.op_report)
        _perf.register_owner("params", lambda: self._params)
        _perf.register_owner("kv_pages", lambda: self._state)

        self._started = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle-genserve-decode")
        self._thread.start()
        return self

    def op_report(self, *, measured_step_ms=None, trace_dir=None):
        """Per-op attribution of the AOT-compiled decode step
        (monitor/perf.py).  Measured time defaults to the inter-token
        p50 — in steady state one decode iteration IS the inter-token
        gap.  Reads only the compiled executable's HLO; never touches
        the live (donated) decode state."""
        if self._decode_exec is None:
            raise RuntimeError("op_report() before start()")
        ca = self._decode_exec.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
        if measured_step_ms is None:
            gaps = sorted(self.metrics._gaps)
            if gaps:
                measured_step_ms = gaps[len(gaps) // 2] * 1e3
        from ..monitor import perf as _perf

        return _perf.build_report(self._decode_exec, name="decode",
                                  cost_analysis=dict(ca),
                                  measured_step_ms=measured_step_ms,
                                  trace_dir=trace_dir)

    # -- request intake ----------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        raise ValueError(
            f"prompt length {n} exceeds the largest prompt bucket "
            f"{self.prompt_buckets[-1]}")

    def submit(self, prompt, max_new_tokens=32, *, do_sample=False,
               temperature=1.0, top_k=0, seed=0, eos_token_id=None,
               deadline_ms=None, span=None) -> GenerationHandle:
        """Enqueue one prompt (1-D int token ids).  Returns a streaming
        :class:`GenerationHandle`.  Raises QueueFullError under
        backpressure, EngineStoppedError once draining/stopped, and
        ValueError for requests the cache geometry cannot hold.

        `span`: an open request span to hang the engine's gen.queued /
        gen.prefill / gen.decode children from (the HTTP server passes
        its adopted server.generate span); without one, a sampled root
        span is started when the process tracer is enabled."""
        if self._draining or self._stopped:
            self.metrics.count("rejected_draining")
            raise EngineStoppedError("generation engine is draining — no "
                                     "new requests accepted")
        if not self._started:
            raise EngineStoppedError("generation engine not started — "
                                     "call start()")
        prompt = np.array(prompt, np.int32).reshape(-1)
        L = int(prompt.shape[0])
        if L < 1:
            raise ValueError("empty prompt")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        bucket = self._bucket_for(L)
        if L + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt {L} + max_new_tokens {max_new_tokens} exceeds "
                f"max_seq_len {self.max_seq_len}")
        top_k = int(top_k)
        if top_k > self.max_top_k:
            raise ValueError(f"top_k {top_k} exceeds max_top_k "
                             f"{self.max_top_k}")
        eos = self.geometry.vocab_size if eos_token_id is None \
            else int(eos_token_id)
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        own_span = False
        if span is not None and not span.sampled:
            span = None
        elif span is None:
            tracer = _tracing.default_tracer()
            if tracer.enabled:
                root = tracer.start_span(
                    "genserve.request",
                    attrs={"prompt_len": L,
                           "max_new_tokens": max_new_tokens})
                if root.sampled:
                    span, own_span = root, True
        req = _GenRequest(self, prompt, bucket, max_new_tokens,
                          bool(do_sample), float(temperature), top_k,
                          int(seed), eos, deadline, span=span,
                          own_span=own_span)
        if span is not None:
            # attached BEFORE enqueue: the decode thread may admit the
            # request (and close this child) before put_nowait returns
            req.span_queue = span.child("gen.queued", bucket=bucket)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.count("rejected_queue_full")
            req.end_spans("rejected_queue_full")
            raise QueueFullError(
                f"generation queue at capacity ({self.queue_depth}); "
                "retry with backoff") from None
        self._idle.clear()
        self.metrics.count("admitted")
        return req.handle

    def generate(self, prompt, max_new_tokens=32, timeout=None, **kw):
        """Synchronous convenience: submit + result."""
        return self.submit(prompt, max_new_tokens, **kw).result(timeout)

    # -- the decode loop ---------------------------------------------------
    def _wake(self):
        try:
            self._queue.put_nowait(_WAKE)
        except queue.Full:
            pass

    def _run(self):
        try:
            while True:
                self._pull_requests()
                self._sweep_backlog()
                self._admit_ready()
                self._preempt_swept()
                occupied = self._sched.occupied
                self.metrics.set_occupancy(len(occupied))
                if occupied and not self._stopped:
                    toks, fin = self.step()
                    self._distribute(toks, fin)
                    continue
                if self._queue.empty() and not self._backlog:
                    self._idle.set()
                    if self._draining or self._stopped:
                        return
        except BaseException as e:  # pragma: no cover - last-resort:
            # never die silently
            logger.exception("generation decode loop crashed")
            try:
                from ..monitor import perf as _perf

                if _perf.is_oom(e):
                    # the decode thread CAUGHT the failure, so the
                    # crash excepthook will never see it — dump the
                    # census + op table postmortem here
                    _perf.oom_postmortem(e)
            except Exception:  # noqa: BLE001 - never mask the crash
                pass
            self._stopped = True
            self._fail_everything(EngineStoppedError(
                "generation decode loop crashed"))
            self._idle.set()
            raise

    def _pull_requests(self):
        """Move queued requests to the backlog; block only when idle."""
        block = (not self._sched.occupied and not self._backlog
                 and not (self._draining or self._stopped))
        try:
            req = self._queue.get(block=block)
        except queue.Empty:
            return
        if req is not _WAKE:
            self._backlog.append(req)
        while True:
            try:
                r2 = self._queue.get_nowait()
            except queue.Empty:
                return
            if r2 is not _WAKE:
                self._backlog.append(r2)

    def _sweep_backlog(self):
        now = time.monotonic()
        keep = collections.deque()
        for req in self._backlog:
            if req.cancelled:
                self.metrics.count("cancelled")
                req.end_spans("cancelled")
                req.handle._finish()
            elif req.deadline is not None and now > req.deadline:
                self.metrics.count("deadline_expired")
                req.end_spans("deadline_expired")
                req.handle._finish(DeadlineExceededError(
                    "request deadline passed while queued"))
            else:
                keep.append(req)
        self._backlog = keep

    def _admit_ready(self):
        while self._backlog and self._sched.has_free() \
                and not self._stopped:
            req = self._backlog.popleft()
            slot = self._sched.admit(req)
            try:
                self._admit(req, slot)
            except Exception as e:  # noqa: BLE001 - fail THIS request,
                # keep the decode loop alive for the others
                logger.exception("generation admission failed")
                self.metrics.count("errors")
                self._sched.retire(slot)
                req.end_spans("error")
                req.handle._finish(e)

    def _admit(self, req: _GenRequest, slot: int):
        """Prefill + insert: seed the slot's cache rows and arm the lane
        with its first sampled token — the request joins the in-flight
        batch at this iteration boundary."""
        L = len(req.prompt)
        if req.span_queue is not None:
            req.span_queue.end(status="ok")
            req.span_queue = None
        sp_prefill = (req.span.child("gen.prefill", bucket=req.bucket,
                                     prompt_len=L, slot=slot)
                      if req.span is not None else None)
        ids = np.zeros((1, req.bucket), np.int32)
        ids[0, :L] = req.prompt
        with RecordEvent("paddle.genserve/prefill"):
            k_new, v_new, logits = self._prefill_execs[req.bucket](
                self._params, ids, np.int32(L))
            state, tok1 = self._insert_execs[req.bucket](
                self._state, np.int32(slot), k_new, v_new, logits,
                np.int32(L), np.int32(req.seed), np.bool_(req.do_sample),
                np.float32(req.temperature), np.int32(req.top_k),
                np.int32(L + req.max_new_tokens), np.int32(req.eos))
        self._state = state
        with host_fetch():
            t1 = int(np.array(tok1, copy=True))
        if sp_prefill is not None:
            sp_prefill.end(status="ok")
        now = time.monotonic()
        req.t_last_token = now
        req.handle._push(t1)
        if req.span is not None:
            req.span.event("first_token", slot=slot)
        self.metrics.observe_ttft(now - req.handle.t_submit)
        self.metrics.observe_tokens(1)
        if req.max_new_tokens == 1 or t1 == req.eos:
            self._release([slot])
            self._sched.retire(slot)
            self.metrics.count("retired")
            req.end_spans("ok")
            req.handle._finish()
        elif req.span is not None:
            req.span_decode = req.span.child("gen.decode", slot=slot)

    def _release(self, slots):
        mask = np.zeros((self.max_slots,), np.bool_)
        for s in slots:
            mask[s] = True
        self._state = self._release_exec(self._state, mask)

    def _preempt_swept(self):
        swept = self._sched.sweep()
        if not swept:
            return
        self._release([slot for slot, _, _ in swept])
        for slot, req, reason in swept:
            self._sched.retire(slot)
            self.metrics.count(reason)
            self.metrics.count("preempted")
            req.end_spans(reason)
            req.handle._finish(
                None if reason == "cancelled" else DeadlineExceededError(
                    "request deadline passed mid-decode"))

    def step(self):
        """ONE decode iteration: every in-flight lane advances a token.
        The state pytree is donated to the compiled executable (the KV
        cache is rewritten on device, never fetched); only the sampled
        token ids and finished mask cross to host, under host_fetch()."""
        self._iter += 1
        chaos.on_step(self._iter)   # fault-injection seam (utils/chaos)
        with RecordEvent("paddle.genserve/decode"):
            state, toks, fin = self._decode_exec(self._params, self._state)
        self._state = state
        with host_fetch():
            toks_np = np.array(toks, copy=True)
            fin_np = np.array(fin, copy=True)
        return toks_np, fin_np

    def _distribute(self, toks_np, fin_np):
        now = time.monotonic()
        occupied = list(self._sched.occupied.items())
        self.metrics.observe_tokens(len(occupied))
        for slot, req in occupied:
            tok = int(toks_np[slot])
            if req.t_last_token is not None:
                self.metrics.observe_inter_token(now - req.t_last_token)
            req.t_last_token = now
            req.handle._push(tok)
            if req.span_decode is not None:
                # host ints only — toks/fin were fetched in step()
                req.span_decode.event("token", i=len(req.handle.tokens))
            if bool(fin_np[slot]):
                self._sched.retire(slot)
                self.metrics.count("retired")
                req.end_spans("ok")
                req.handle._finish()

    def _fail_everything(self, exc):
        for dq in (self._backlog,):
            while dq:
                req = dq.popleft()
                req.end_spans("error")
                req.handle._finish(exc)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not _WAKE:
                req.end_spans("error")
                req.handle._finish(exc)
        for slot in list(self._sched.occupied):
            req = self._sched.retire(slot)
            req.end_spans("error")
            req.handle._finish(exc)

    # -- shutdown ----------------------------------------------------------
    def drain(self, timeout=None) -> bool:
        """Graceful: reject new work, finish every queued and in-flight
        generation, stop the decode loop.  True when fully drained."""
        self._draining = True
        if self._thread is None:
            return True
        self._wake()
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        drained = self._idle.wait(timeout)
        self._thread.join(None if deadline is None
                          else max(0.0, deadline - time.monotonic()))
        alive = self._thread.is_alive()
        if not alive:
            self._thread = None
        # a submit racing the drain flag can slip a request in after the
        # loop's final empty-check — fail it, never strand its handle
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is _WAKE:
                continue
            drained = False
            if not req.handle.done:
                req.end_spans("rejected_draining")
                req.handle._finish(EngineStoppedError(
                    "request arrived during drain"))
        return drained and not alive

    def stop(self):
        """Hard stop: fail everything queued and in-flight."""
        self._stopped = True
        self._draining = True
        thread = self._thread
        if thread is not None:
            self._wake()
            thread.join(5.0)
            if not thread.is_alive():
                self._thread = None
        self._fail_everything(EngineStoppedError("engine stopped"))

    @property
    def draining(self) -> bool:
        return self._draining

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        if exc[0] is None:
            self.drain(timeout=30.0)
        self.stop()
        return False


def main(argv=None):
    """Standalone generation server over a randomly initialized GPT —
    the tools/serve_smoke.sh concurrent-decode fixture (real deployments
    build a GenerationEngine around trained weights, or call
    ``Model.serve_generate()``)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="paddle_tpu generation server (continuous-batching "
                    "decode with a device-resident KV cache)")
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--heads", type=int, default=4)
    parser.add_argument("--vocab", type=int, default=211)
    parser.add_argument("--max-seq-len", type=int, default=64)
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--prompt-buckets", default="8,16")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8867,
                        help="0 picks a free port (printed on stdout)")
    args = parser.parse_args(argv)

    import logging as _logging

    _logging.basicConfig(level=_logging.INFO)
    import paddle_tpu as paddle
    from ..models.gpt import GPTConfig, GPTForCausalLM
    from .server import ServingServer

    paddle.seed(args.seed)
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    max_position_embeddings=args.max_seq_len,
                    dropout=0.0, attn_dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = GenerationEngine(model, max_slots=args.slots,
                              max_seq_len=args.max_seq_len,
                              prompt_buckets=args.prompt_buckets)
    server = ServingServer(None, gen_engine=engine, host=args.host,
                           port=args.port).start()
    # parse-friendly readiness line (tools/serve_smoke.sh greps it)
    print(f"paddle_tpu.serving listening on {server.url}", flush=True)
    return server.wait()


if __name__ == "__main__":
    import sys

    sys.exit(main())
