"""Continuous-batching autoregressive generation engine.

The serving-side counterpart of the training engine's donation
discipline: the reference stack served autoregressive traffic through
fused_multi_transformer's CacheKV decode behind AnalysisPredictor's
per-request generation loop; this module is that path rebuilt for XLA's
shape discipline, in the Orca iteration-level-scheduling shape:

  * **prefill/decode split** — each admitted prompt runs ONE prefill
    (compiled per prompt-length bucket through the same AOT machinery as
    the Predictor's shape buckets) that seeds its slot's pages of the
    device-resident PAGED KV cache; then a single donated, jitted
    **decode step** advances ALL in-flight sequences one token per
    iteration, allocating fresh tail pages in-graph off the free-list
    register as lanes cross page boundaries.
  * **continuous batching** — the scheduler admits queued requests into
    free slots at iteration boundaries (no waiting for the batch to
    drain) once the page pool can reserve their worst case, retires
    lanes on EOS/max_new_tokens (their private pages return to the pool
    in the same decode step), and preempts lanes on
    deadline/cancellation; a request admitted mid-decode produces tokens
    bitwise-identical to running alone (tested).
  * **prefix sharing** — identical tokenized prompt prefixes occupy the
    pool ONCE (serving/prefix_cache.py): a hit maps the cached
    read-only pages into the slot's page table and prefills only the
    suffix, attending over the cached prefix K/V.
  * **zero steady-state compiles, zero cache round-trips** — every
    executable (decode, release, reclaim, per-bucket prefill/insert) is
    AOT lowered+compiled at ``start()`` via ``inference.aot_compile``;
    the decode state pytree (serving/kv_cache.py) is donated on every
    transition, so the KV pool lives on device across iterations and
    only the sampled token ids are fetched (under ``host_fetch()``).
  * **layout-aware** — pass ``mesh=`` (+ an optional PR-8 ``SpecLayout``)
    and the engine serves a tensor-parallel model from one process:
    params resolve through the layout's PartitionSpec table, the page
    pool's head axis shards over ``tp``, and every executable is
    compiled with NamedSharding in/out (out-shardings pinned to
    in-shardings, so donation holds under GSPMD).

Per-slot sampling (greedy / temperature / top-k, per-request seed)
reproduces ``GPTForCausalLM.generate``'s exact PRNG chain — one
``split`` at admission, one per decode iteration — which is what makes
engine output comparable token-for-token with the solo path.
"""
from __future__ import annotations

import collections
import logging
import queue
import threading
import time

import numpy as np

from ..framework import flags as _flags
from ..framework.transfer import host_fetch
from ..monitor import tracing as _tracing
from ..utils import chaos
from ..utils.profiler import RecordEvent
from .engine import (DeadlineExceededError, EngineStoppedError,
                     QueueFullError)
from .kv_cache import (CacheGeometry, admit_slot, make_state, push_pages,
                       reclaim_pages, release_slots, state_specs,
                       take_pages, write_prompt)
from .metrics import GenerationMetrics
from .prefix_cache import PrefixCache
from .scheduler import SlotScheduler

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["GenerationEngine", "GenerationHandle"]

_WAKE = object()   # queue sentinel: wakes an idle-blocked decode loop
_END = object()    # handle sentinel: no more tokens


class GenerationHandle:
    """Per-request streaming face: tokens arrive as the decode loop
    produces them; iterate (``for tok in handle``), poll
    (``next_token``), or block for everything (``result``)."""

    def __init__(self, prompt_len: int, max_new_tokens: int):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.tokens: list[int] = []       # appended by the decode thread
        self._q: queue.Queue = queue.Queue()
        self._done = threading.Event()
        self._error: BaseException | None = None
        self._req = None                  # backref set by the engine
        self.t_submit = time.monotonic()
        self.t_first_token = None

    # -- consuming ---------------------------------------------------------
    def next_token(self, timeout=None):
        """Next generated token id, or None when the stream has ended
        (raises the request's error, if it failed)."""
        if self._done.is_set() and self._q.empty():
            if self._error is not None:
                raise self._error
            return None
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"no token within {timeout:g}s") from None
        if item is _END:
            if self._error is not None:
                raise self._error
            return None
        return item

    def __iter__(self):
        while True:
            tok = self.next_token()
            if tok is None:
                return
            yield tok

    def result(self, timeout=None) -> list[int]:
        """Block until the request finishes; the generated token ids
        (prompt excluded).  Raises on deadline expiry / engine failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"generation not finished in {timeout:g}s")
        if self._error is not None:
            raise self._error
        return list(self.tokens)

    # -- control -----------------------------------------------------------
    def cancel(self):
        """Ask the engine to preempt this request at the next iteration
        boundary (its slot is freed; tokens produced so far remain)."""
        req = self._req
        if req is not None:
            req.cancelled = True
            req.engine._wake()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def error(self):
        return self._error

    @property
    def ttft_ms(self):
        if self.t_first_token is None:
            return None
        return (self.t_first_token - self.t_submit) * 1e3

    # -- engine side -------------------------------------------------------
    def _push(self, tok: int):
        if self.t_first_token is None:
            self.t_first_token = time.monotonic()
        self.tokens.append(tok)
        self._q.put(tok)

    def _finish(self, error: BaseException | None = None):
        if self._done.is_set():
            return
        self._error = error
        self._done.set()
        self._q.put(_END)


class _GenRequest:
    __slots__ = ("prompt", "bucket", "max_new_tokens", "do_sample",
                 "temperature", "top_k", "seed", "resume_pos", "eos",
                 "deadline", "handle", "engine", "cancelled",
                 "t_last_token", "span", "own_span", "span_queue",
                 "span_decode", "prefilling", "prefill_cursor",
                 "chunk_row", "j_hit", "pin_final")

    def __init__(self, engine, prompt, bucket, max_new_tokens, do_sample,
                 temperature, top_k, seed, eos, deadline, span=None,
                 own_span=False, resume_pos=0):
        self.engine = engine
        self.prompt = prompt               # np.int32 [L]
        self.bucket = bucket               # padded prompt length Sp
        self.max_new_tokens = max_new_tokens
        self.do_sample = do_sample
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.resume_pos = resume_pos       # tokens a dead replica emitted
        self.eos = eos                     # int; vocab_size == never
        self.deadline = deadline           # absolute monotonic or None
        self.cancelled = False
        self.t_last_token = None
        self.span = span                   # request span (sampled or None)
        self.own_span = own_span           # engine owns span's end()
        self.span_queue = None             # "gen.queued" child
        self.span_decode = None            # "gen.decode" child
        self.prefilling = False            # chunked prefill in flight
        self.prefill_cursor = 0            # tokens already prefilled
        self.chunk_row = None              # slot's page row so far (np)
        self.j_hit = 0                     # prefix-cache pages mapped
        self.pin_final = 0                 # pinned count once armed
        self.handle = GenerationHandle(len(prompt), max_new_tokens)
        self.handle._req = self

    def end_spans(self, status: str):
        """Close any open child spans and settle the request span with a
        terminal status; the parent is ended here only when the engine
        owns it (direct submit — HTTP requests end theirs upstream)."""
        for s in (self.span_queue, self.span_decode):
            if s is not None:
                s.end(status=status)
        self.span_queue = self.span_decode = None
        if self.span is not None:
            self.span.set_attr("status", status)
            if self.own_span:
                self.span.end()
            self.span = None


class GenerationEngine:
    """Continuous-batching decode over a device-resident paged KV cache.

    Args:
      model: a causal-LM Layer exposing ``slot_prefill`` /
        ``slot_decode_paged`` (models/gpt.py GPTForCausalLM) and a
        ``cfg`` with num_layers / num_heads / hidden_size / vocab_size /
        max_position_embeddings.
      max_slots: in-flight sequences per decode iteration
        (``FLAGS_genserve_max_slots``).
      max_seq_len: per-slot sequence cap S_max >= prompt + new tokens
        (``FLAGS_genserve_max_seq_len``).
      prompt_buckets: admitted prompt-length grid, list or "8,16,32"
        (``FLAGS_genserve_prompt_buckets``); one prefill+insert
        executable pair is AOT-compiled per bucket at start().
      queue_depth: bounded admission queue
        (``FLAGS_genserve_queue_depth``) — ``submit`` raises
        :class:`QueueFullError` beyond it.
      max_top_k: largest per-request top_k accepted (the sampling
        executable carries a static top-k width).
      page_size: tokens per KV page (``FLAGS_genserve_page_size``).
      num_pages: page-pool capacity (``FLAGS_genserve_num_pages``);
        0 sizes it dense-equivalently (max_slots * pages_per_slot) —
        smaller pools oversubscribe slots against actual footprint and
        the scheduler queues admissions that cannot reserve their
        worst case.
      prefix_cache: share identical tokenized prompt prefixes as
        refcounted read-only pages (``FLAGS_genserve_prefix_cache``);
        hits skip prefill for the shared pages.
      mesh: optional jax Mesh (or a {"tp": 2}-style dict) — serve a
        tensor-parallel model from one engine.
      layout: optional distributed.SpecLayout resolving param placements
        (defaults to ``SpecLayout()`` when a mesh is given).

    Lifecycle mirrors ServingEngine: ``start()`` compiles every
    executable (steady state never compiles), ``submit()`` returns a
    streaming :class:`GenerationHandle`, ``drain()`` finishes in-flight
    decodes and rejects new work, ``stop()`` kills the loop.
    """

    def __init__(self, model, *, max_slots=None, max_seq_len=None,
                 prompt_buckets=None, queue_depth=None, max_top_k=64,
                 page_size=None, num_pages=None, prefix_cache=None,
                 mesh=None, layout=None, draft_model=None,
                 spec_tokens=None, prefill_chunk=None):
        from ..hapi.model import Model as _HapiModel

        if isinstance(model, _HapiModel):
            model = model.network
        for req_attr in ("slot_prefill", "slot_decode_paged", "cfg"):
            if not hasattr(model, req_attr):
                raise TypeError(
                    f"GenerationEngine needs a model with `{req_attr}` "
                    "(a causal LM with the slot-batched paged KV-cache "
                    "decode path, e.g. models.GPTForCausalLM); got "
                    f"{type(model).__name__}")
        self.model = model
        cfg = model.cfg
        self.max_slots = int(max_slots
                             or _flags.flag("FLAGS_genserve_max_slots", 4))
        self.max_seq_len = int(
            max_seq_len or _flags.flag("FLAGS_genserve_max_seq_len", 256))
        if self.max_seq_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"max_position_embeddings {cfg.max_position_embeddings}")
        if prompt_buckets is None:
            prompt_buckets = _flags.flag("FLAGS_genserve_prompt_buckets",
                                         "16,32,64")
        if isinstance(prompt_buckets, str):
            prompt_buckets = [int(s) for s in prompt_buckets.split(",")
                              if s.strip()]
        self.prompt_buckets = sorted(set(int(b) for b in prompt_buckets))
        if not self.prompt_buckets or self.prompt_buckets[0] < 1:
            raise ValueError(f"invalid prompt buckets {prompt_buckets!r}")
        if self.prompt_buckets[-1] >= self.max_seq_len:
            raise ValueError(
                f"largest prompt bucket {self.prompt_buckets[-1]} leaves "
                f"no room to generate within max_seq_len {self.max_seq_len}")
        self.queue_depth = int(
            queue_depth or _flags.flag("FLAGS_genserve_queue_depth", 128))
        self.max_top_k = int(max_top_k)
        page_size = int(page_size
                        or _flags.flag("FLAGS_genserve_page_size", 16))
        if num_pages is None:
            num_pages = int(_flags.flag("FLAGS_genserve_num_pages", 0))
        if prefix_cache is None:
            prefix_cache = bool(int(
                _flags.flag("FLAGS_genserve_prefix_cache", 1)))

        # speculative decode: a draft model proposes spec_tokens per
        # iteration, the target verifies them in one batched step
        if isinstance(draft_model, _HapiModel):
            draft_model = draft_model.network
        if draft_model is not None:
            for req_attr in ("slot_prefill", "slot_decode_paged",
                             "slot_prefill_prefix", "cfg"):
                if not hasattr(draft_model, req_attr):
                    raise TypeError(
                        f"draft_model needs `{req_attr}`; got "
                        f"{type(draft_model).__name__}")
            dcfg = draft_model.cfg
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dcfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}")
            if self.max_seq_len > dcfg.max_position_embeddings:
                raise ValueError(
                    f"max_seq_len {self.max_seq_len} exceeds the draft "
                    "model's max_position_embeddings "
                    f"{dcfg.max_position_embeddings}")
            if mesh is not None:
                raise ValueError(
                    "speculative decode under a mesh is not supported "
                    "yet — drop draft_model or mesh")
        self.draft_model = draft_model
        if spec_tokens is None:
            spec_tokens = int(_flags.flag("FLAGS_genserve_spec_tokens", 4))
        self.spec_tokens = int(spec_tokens) if draft_model is not None \
            else 0
        if draft_model is not None and self.spec_tokens < 1:
            raise ValueError(
                f"spec_tokens must be >= 1 with a draft model, got "
                f"{self.spec_tokens}")

        # chunked prefill: long prompts stream into the cache
        # prefill_chunk tokens per decode iteration (0 = whole-prompt)
        if prefill_chunk is None:
            prefill_chunk = int(
                _flags.flag("FLAGS_genserve_prefill_chunk", 0))
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk:
            if self.prefill_chunk % page_size:
                raise ValueError(
                    f"prefill_chunk {self.prefill_chunk} must be a "
                    f"multiple of page_size {page_size} (chunk cursors "
                    "resume at page boundaries)")
            if self.prefill_chunk > self.prompt_buckets[-1]:
                raise ValueError(
                    f"prefill_chunk {self.prefill_chunk} exceeds the "
                    f"largest prompt bucket {self.prompt_buckets[-1]}")

        draft_kw = {}
        if draft_model is not None:
            draft_kw = dict(
                draft_layers=dcfg.num_layers,
                draft_num_heads=dcfg.num_heads,
                draft_head_dim=dcfg.hidden_size // dcfg.num_heads)
        self.geometry = CacheGeometry(
            num_layers=cfg.num_layers, max_slots=self.max_slots,
            max_seq_len=self.max_seq_len, num_heads=cfg.num_heads,
            head_dim=cfg.hidden_size // cfg.num_heads,
            vocab_size=cfg.vocab_size, page_size=page_size,
            num_pages=int(num_pages), **draft_kw)
        self.metrics = GenerationMetrics(
            max_slots=self.max_slots, num_pages=self.geometry.num_pages)
        self._prefix = (PrefixCache(page_size) if prefix_cache else None)
        self._slot_pins: dict[int, list] = {}   # slot -> pinned page ids
        self._queue: queue.Queue = queue.Queue(self.queue_depth)
        self._backlog: collections.deque = collections.deque()
        self._sched = SlotScheduler(self.max_slots,
                                    num_pages=self.geometry.num_pages)
        if mesh is not None and not hasattr(mesh, "axis_names"):
            # {"tp": 2}-style dict: build a mesh over exactly the
            # devices the shape needs (the process may expose more)
            import jax

            from ..distributed.mesh import build_mesh

            dims = [int(v) for v in dict(mesh).values()]
            devices = None
            if all(d > 0 for d in dims):
                n = 1
                for d in dims:
                    n *= d
                devices = jax.devices()[:n]
            mesh = build_mesh(dict(mesh), devices=devices)
        self._mesh = mesh
        if layout is None and mesh is not None:
            from ..distributed.layout import SpecLayout

            layout = SpecLayout()
        self._layout = layout
        self._thread = None
        self._started = False
        self._draining = False
        self._stopped = False
        self._idle = threading.Event()
        self._idle.set()
        self._iter = 0
        self.compile_count = 0
        self._state = None
        self._params = None
        self._buffers = None
        self._decode_exec = None
        self._spec_exec = None
        self._release_exec = None
        self._reclaim_exec = None
        self._prefill_execs = {}
        self._insert_execs = {}
        self._insert_prefix_execs = {}
        self._chunk_execs = {}
        self._draft_params = None
        self._draft_buffers = None

    # -- warmup: build + AOT-compile every executable ----------------------
    def start(self) -> "GenerationEngine":
        if self._started:
            return self
        import jax
        import jax.numpy as jnp

        from .. import inference
        from ..nn.layer_base import functional_call, state_pytrees
        from ..tensor import Tensor

        self.model.eval()
        params, buffers = state_pytrees(self.model)
        geom = self.geometry
        V = geom.vocab_size
        k_max = min(self.max_top_k, V)
        ps, pps = geom.page_size, geom.pages_per_slot
        num_pages, seq_cap = geom.num_pages, geom.max_seq_len
        # static prefix extent of the hit-path executables: the largest
        # full-page prefix any admitted prompt can share
        pfx_pages = min(pps, -(-self.prompt_buckets[-1] // ps))

        # sharding plan: None entries (no mesh) keep today's lowering
        mesh, layout = self._mesh, self._layout
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            rep = NamedSharding(mesh, P())
            pool_sh = NamedSharding(mesh, layout.prune(
                layout.kv_page_spec(), geom.pool_shape, mesh))
            kv_sh = NamedSharding(mesh, layout.prune(
                P(None, None, layout.tp_axis, None),
                (geom.num_layers, 1, geom.num_heads, geom.head_dim), mesh))
            pspecs = layout.resolve(
                {n: np.shape(a) for n, a in params.items()}, mesh,
                warn=False)
            params = {n: jax.device_put(a, NamedSharding(mesh, pspecs[n]))
                      for n, a in params.items()}
            buffers = {n: jax.device_put(a, rep)
                       for n, a in buffers.items()}
        else:
            rep = pool_sh = kv_sh = None
        self._params, self._buffers = params, buffers
        draft = self.draft_model
        K = self.spec_tokens
        if draft is not None:
            draft.eval()
            dparams, dbuffers = state_pytrees(draft)
            self._draft_params, self._draft_buffers = dparams, dbuffers
        else:
            dparams = dbuffers = None

        def sample_token(lg, key, do_sample, temp, top_k):
            """Per-lane sampling, chain-compatible with generate():
            greedy = argmax of raw logits; sampling = temperature scale,
            static-width top-k cutoff (dynamic k), categorical over the
            [1, V] row exactly as the solo path draws it."""
            greedy = jnp.argmax(lg).astype(jnp.int32)
            lg2 = lg / jnp.maximum(temp, 1e-6)
            vals = jax.lax.top_k(lg2, k_max)[0]
            kth = vals[jnp.clip(top_k - 1, 0, k_max - 1)]
            lg3 = jnp.where((top_k > 0) & (lg2 < kth),
                            jnp.finfo(lg2.dtype).min, lg2)
            samp = jax.random.categorical(
                key, lg3[None, :])[0].astype(jnp.int32)
            return jnp.where(do_sample, samp, greedy)

        def resume_chain(seed, resume_pos):
            """Mid-stream failover (router re-admission): fast-forward
            the per-request PRNG chain past the ``resume_pos`` tokens a
            dead replica already emitted.  The chain is k_0=PRNGKey(seed)
            with (k_i, s_i)=split(k_{i-1}) and token i drawn from s_i, so
            after the fast-forward the admission split below yields
            exactly (k_{P+1}, s_{P+1}) — the first resumed sample is the
            token the uninterrupted run would have drawn next, and the
            chain state is identical thereafter.  resume_pos=0 is the
            normal (non-resumed) admission, bitwise today's behavior."""
            key = jax.random.PRNGKey(seed)
            return jax.lax.fori_loop(
                0, resume_pos, lambda _, k: jax.random.split(k)[0], key)

        model, geometry = self.model, geom

        def target_prefill(params, ids, length):
            out, _ = functional_call(
                model, params, (Tensor(ids), length), buffers=buffers,
                mutable=False, method="slot_prefill")
            return out                     # (k [L,Sp,nh,hd], v, logits [V])

        if draft is None:
            prefill_step = target_prefill
        else:
            def prefill_step(params, dparams, ids, length):
                # one executable fills BOTH pools: the draft's KV must
                # cover the prompt so its proposal chain can attend it
                k, v, lg = target_prefill(params, ids, length)
                (dk, dv, _), _ = functional_call(
                    draft, dparams, (Tensor(ids), length),
                    buffers=dbuffers, mutable=False,
                    method="slot_prefill")
                return k, v, lg, dk, dv

        def insert_step(state, slot, k_new, v_new, logits, length, seed,
                        resume_pos, do_sample, temp, top_k, stop_pos, eos,
                        pinned, *draft_kv):
            # prefix-miss admission: every mapped page is freshly
            # allocated and written (shared_n = 0)
            no_shared = jnp.full((pps,), -1, jnp.int32)
            state, row = write_prompt(state, slot, k_new, v_new, length,
                                      no_shared, jnp.int32(0), *draft_kv)
            key, sub = jax.random.split(resume_chain(seed, resume_pos))
            tok1 = sample_token(logits, sub, do_sample, temp, top_k)
            state = admit_slot(state, slot, tok1, length, key, do_sample,
                               temp, top_k, stop_pos, eos, pinned)
            return state, tok1, row

        def suffix_prefill(params, dparams, state, ids, shared_ids,
                           shared_n, length):
            # gather the already-resident prefix K/V from the pool(s)
            # and prefill ONLY the suffix, attending over it — shared
            # by the prefix-hit admission path and every prefill chunk
            gidx = jnp.clip(shared_ids[:pfx_pages], 0, num_pages - 1)
            pk = state["kp"][:, gidx].reshape(
                geometry.num_layers, pfx_pages * ps, geometry.num_heads,
                geometry.head_dim)
            pv = state["vp"][:, gidx].reshape(
                geometry.num_layers, pfx_pages * ps, geometry.num_heads,
                geometry.head_dim)
            (k_suf, v_suf, logits), _ = functional_call(
                model, params,
                (Tensor(ids), pk, pv, shared_n * ps, length),
                buffers=buffers, mutable=False,
                method="slot_prefill_prefix")
            if draft is None:
                return k_suf, v_suf, logits, ()
            dL, _, _, dnh, dhd = geometry.draft_pool_shape
            dpk = state["dkp"][:, gidx].reshape(dL, pfx_pages * ps,
                                                dnh, dhd)
            dpv = state["dvp"][:, gidx].reshape(dL, pfx_pages * ps,
                                                dnh, dhd)
            (dk_suf, dv_suf, _), _ = functional_call(
                draft, dparams,
                (Tensor(ids), dpk, dpv, shared_n * ps, length),
                buffers=dbuffers, mutable=False,
                method="slot_prefill_prefix")
            return k_suf, v_suf, logits, (dk_suf, dv_suf)

        def _insert_prefix(params, dparams, state, slot, ids, shared_ids,
                           shared_n, length, seed, resume_pos, do_sample,
                           temp, top_k, stop_pos, eos, pinned):
            # prefix-hit admission: the shared pages are never
            # recomputed; the suffix pages in at the (page-aligned)
            # boundary
            k_suf, v_suf, logits, draft_kv = suffix_prefill(
                params, dparams, state, ids, shared_ids, shared_n,
                length)
            state, row = write_prompt(state, slot, k_suf, v_suf, length,
                                      shared_ids, shared_n, *draft_kv)
            key, sub = jax.random.split(resume_chain(seed, resume_pos))
            tok1 = sample_token(logits, sub, do_sample, temp, top_k)
            state = admit_slot(state, slot, tok1, length, key, do_sample,
                               temp, top_k, stop_pos, eos, pinned)
            return state, tok1, row

        if draft is None:
            def insert_prefix_step(params, state, *a):
                return _insert_prefix(params, None, state, *a)
        else:
            insert_prefix_step = _insert_prefix

        def _chunk(params, dparams, state, slot, ids, shared_ids,
                   shared_n, length, seed, resume_pos, do_sample, temp,
                   top_k, stop_pos, eos, pin_now, pin_final, arm):
            # one prefill chunk: scatter this slice's K/V behind the
            # resumable cursor; ONLY the final chunk (arm=True) samples
            # a real first token and activates the lane.  Until then
            # ``pinned`` stays at the prefix-cache hit count (pin_now)
            # so a cancel/deadline sweep frees every privately written
            # chunk page — the stale-pinned leak this executable exists
            # to prevent; the final chunk raises it to pin_final to
            # protect the pages about to be registered as shared.
            k_suf, v_suf, logits, draft_kv = suffix_prefill(
                params, dparams, state, ids, shared_ids, shared_n,
                length)
            state, row = write_prompt(state, slot, k_suf, v_suf, length,
                                      shared_ids, shared_n, *draft_kv)
            key, sub = jax.random.split(resume_chain(seed, resume_pos))
            tok1 = sample_token(logits, sub, do_sample, temp, top_k)
            pinned = jnp.where(jnp.asarray(arm, bool), pin_final,
                               pin_now)
            state = admit_slot(state, slot, tok1, length, key, do_sample,
                               temp, top_k, stop_pos, eos, pinned,
                               active=arm)
            return state, tok1, row

        if draft is None:
            def chunk_step(params, state, *a):
                return _chunk(params, None, state, *a)
        else:
            chunk_step = _chunk

        def decode_step(params, state):
            lane = jnp.arange(geometry.max_slots)
            pos, active = state["pos"], state["active"]
            ptab = state["ptab"]
            # (1) pop a fresh tail page for lanes whose write position
            # crossed into an unmapped page — in-graph allocation off
            # the free-list register (host reserved the worst case)
            pidx = jnp.clip(pos // ps, 0, pps - 1)
            cur = ptab[lane, pidx]
            need = active & (cur < 0)
            pages, free_count = take_pages(state["free_stack"],
                                           state["free_count"], need)
            ptab = ptab.at[lane, pidx].set(jnp.where(need, pages, cur))
            # (2) one paged-attention token per lane
            (logits, kp, vp), _ = functional_call(
                model, params,
                (state["tok"], pos, active, state["kp"], state["vp"],
                 ptab, seq_cap),
                buffers=buffers, mutable=False, method="slot_decode_paged")
            pair = jax.vmap(jax.random.split)(state["rng"])
            new_keys, subs = pair[:, 0], pair[:, 1]
            toks = jax.vmap(sample_token)(
                logits, subs, state["do_sample"], state["temp"],
                state["top_k"])
            toks = jnp.where(active, toks, state["tok"])
            new_pos = jnp.where(active, pos + 1, pos)
            finished = active & ((toks == state["eos"])
                                 | (new_pos + 1 >= state["stop_pos"]))
            # (3) retire in-graph: finished lanes' PRIVATE pages (table
            # index >= pinned) go back on the free stack; shared prefix
            # pages stay resident for the prefix cache
            col = jnp.arange(pps, dtype=jnp.int32)[None, :]
            freeable = finished[:, None] & (ptab >= 0) \
                & (col >= state["pinned"][:, None])
            free_stack, free_count = push_pages(
                state["free_stack"], free_count,
                jnp.where(freeable, ptab, -1).reshape(-1))
            ptab = jnp.where(finished[:, None], -1, ptab)
            new_state = dict(state, kp=kp, vp=vp, ptab=ptab,
                             free_stack=free_stack, free_count=free_count,
                             tok=toks, pos=new_pos, rng=new_keys,
                             active=active & ~finished)
            return new_state, toks, finished

        def spec_step(params, dparams, state):
            """ONE speculative iteration: the draft model chains K
            greedy proposals, the target scores the committed token +
            all K proposals in one batched verify step, and each greedy
            lane emits the longest agreeing run + the target's first
            divergent token (1..K+1 tokens).  Sampling lanes ride the
            same executable emitting exactly one token from the verify
            chunk's position-0 logits with the unchanged per-lane PRNG
            chain — bitwise the non-speculative distribution.

            Rejected proposals need no rollback: their pages stay
            mapped inside the lane's reservation and the next
            iteration's chain/verify scatter overwrites the dead K/V at
            those positions before any emitted query can attend it.
            """
            lane = jnp.arange(geometry.max_slots)
            pos, active = state["pos"], state["active"]
            stop_pos = state["stop_pos"]
            greedy_lane = ~state["do_sample"]
            ptab = state["ptab"]
            # (1) map every page covering [pos, hi] in one take — the
            # speculation window never writes past the slot's reserved
            # extent (positions clamp at stop_pos - 1)
            hi = jnp.minimum(pos + K, stop_pos - 1)
            col = jnp.arange(pps, dtype=jnp.int32)[None, :]
            need = active[:, None] & (ptab < 0) \
                & (col >= (pos // ps)[:, None]) \
                & (col <= (hi // ps)[:, None])
            pages, free_count = take_pages(
                state["free_stack"], state["free_count"],
                need.reshape(-1))
            ptab = jnp.where(need, pages.reshape(ptab.shape), ptab)
            # (2) draft chain: K+1 sequential one-token steps.  Step i
            # writes chain token c_i's draft K/V at pos+i and (i < K)
            # proposes c_{i+1} = argmax; step K only closes the draft
            # cache for a fully accepted run (its logits are discarded).
            dkp, dvp = state["dkp"], state["dvp"]
            t = state["tok"]
            chain = [t]
            for i in range(K + 1):
                p_i = jnp.minimum(pos + i, stop_pos - 1)
                (dlg, dkp, dvp), _ = functional_call(
                    draft, dparams,
                    (t, p_i, active, dkp, dvp, ptab, seq_cap),
                    buffers=dbuffers, mutable=False,
                    method="slot_decode_paged")
                if i < K:
                    t = jnp.argmax(dlg, axis=-1).astype(jnp.int32)
                    chain.append(t)
            tokens = jnp.stack(chain, axis=1)        # [slots, K+1]
            # (3) target verification: score all K+1 candidates at once
            P = jnp.minimum(
                pos[:, None] + jnp.arange(K + 1, dtype=jnp.int32)[None],
                (stop_pos - 1)[:, None])
            (logits, kp, vp), _ = functional_call(
                model, params,
                (tokens, P, active, state["kp"], state["vp"], ptab,
                 seq_cap),
                buffers=buffers, mutable=False,
                method="slot_verify_paged")
            # (4) accept/emit: outs[:, i] is what the target generates
            # after consuming c_0..c_i; position 0 goes through the
            # full sampling path (== argmax for greedy lanes) so the
            # PRNG chain advances exactly once per iteration
            pair = jax.vmap(jax.random.split)(state["rng"])
            new_keys, subs = pair[:, 0], pair[:, 1]
            outs = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out0 = jax.vmap(sample_token)(
                logits[:, 0], subs, state["do_sample"], state["temp"],
                state["top_k"])
            outs = outs.at[:, 0].set(out0)
            # emitted_i: outs[:, i] is produced this iteration — needs
            # the previous emission alive (not finished) and draft
            # proposal c_i to match what the target just generated;
            # fin_i mirrors the non-speculative stop arithmetic for the
            # equivalent iteration at write position pos + i
            em = active
            emitted, fins = [], []
            for i in range(K + 1):
                if i > 0:
                    em = em & ~fins[i - 1] & greedy_lane \
                        & (tokens[:, i] == outs[:, i - 1])
                fin = (outs[:, i] == state["eos"]) \
                    | (pos + i + 2 >= stop_pos)
                emitted.append(em)
                fins.append(fin)
            emitted = jnp.stack(emitted, axis=1)     # [slots, K+1]
            fins = jnp.stack(fins, axis=1)
            n_emit = emitted.sum(axis=1).astype(jnp.int32)
            new_tok = outs[lane, jnp.maximum(n_emit - 1, 0)]
            new_tok = jnp.where(active, new_tok, state["tok"])
            new_pos = jnp.where(active, pos + n_emit, pos)
            finished = active & (emitted & fins).any(axis=1)
            # (5) retire in-graph, same as the plain decode step
            freeable = finished[:, None] & (ptab >= 0) \
                & (col >= state["pinned"][:, None])
            free_stack, free_count = push_pages(
                state["free_stack"], free_count,
                jnp.where(freeable, ptab, -1).reshape(-1))
            ptab = jnp.where(finished[:, None], -1, ptab)
            new_state = dict(state, kp=kp, vp=vp, dkp=dkp, dvp=dvp,
                             ptab=ptab, free_stack=free_stack,
                             free_count=free_count, tok=new_tok,
                             pos=new_pos, rng=new_keys,
                             active=active & ~finished)
            return new_state, outs, emitted, finished

        def release_step(state, mask):
            return release_slots(state, mask)

        def reclaim_step(state, pages):
            return reclaim_pages(state, pages)

        self._state = make_state(geom)
        if mesh is not None:
            state_sh = {k: (pool_sh if k in ("kp", "vp") else rep)
                        for k in self._state}
            self._state = {k: jax.device_put(a, state_sh[k])
                           for k, a in self._state.items()}
        else:
            state_sh = None
        sspec = state_specs(self._state, shardings=state_sh)
        if mesh is not None:
            pspec = {n: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                             sharding=a.sharding)
                     for n, a in params.items()}

            def sds(shape, dtype, sh=rep):
                return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)
        else:
            pspec = inference.spec_tree(params)

            def sds(shape, dtype, sh=None):
                return jax.ShapeDtypeStruct(shape, dtype)
        dpspec = (inference.spec_tree(dparams)
                  if draft is not None else None)  # draft => no mesh
        i32 = sds((), np.int32)
        f32 = sds((), np.float32)
        b1 = sds((), np.bool_)
        pvec = sds((pps,), np.int32)
        kv_dt = np.dtype(geometry.dtype)
        out_state = state_sh if mesh is not None else None

        def outs(*tail):
            # out-shardings pinned to in-shardings (donation contract);
            # None (no mesh) keeps the default lowering
            if mesh is None:
                return None
            return (out_state,) + tail

        chunk_bucket = (self._bucket_for(self.prefill_chunk)
                        if self.prefill_chunk else 0)
        with RecordEvent("paddle.genserve/warmup"):
            if K:
                self._spec_exec = inference.aot_compile(
                    spec_step, (pspec, dpspec, sspec),
                    donate_argnums=(2,))
            else:
                self._decode_exec = inference.aot_compile(
                    decode_step, (pspec, sspec), donate_argnums=(1,),
                    out_shardings=outs(rep, rep))
            self.compile_count += 1
            self._release_exec = inference.aot_compile(
                release_step, (sspec, sds((self.max_slots,), np.bool_)),
                donate_argnums=(0,), out_shardings=out_state)
            self.compile_count += 1
            if self._prefix is not None:
                self._reclaim_exec = inference.aot_compile(
                    reclaim_step, (sspec, pvec), donate_argnums=(0,),
                    out_shardings=out_state)
                self.compile_count += 1
            dpre = (dpspec,) if draft is not None else ()
            for sp in self.prompt_buckets:
                ids = sds((1, sp), np.int32)
                kv = sds((geom.num_layers, sp, geom.num_heads,
                          geom.head_dim), kv_dt, kv_sh)
                lg = sds((V,), np.float32)
                dkv_in = ()
                if draft is not None:
                    dkv = sds((geom.draft_layers, sp,
                               geom.draft_num_heads,
                               geom.draft_head_dim), kv_dt)
                    dkv_in = (dkv, dkv)
                self._prefill_execs[sp] = inference.aot_compile(
                    prefill_step, (pspec,) + dpre + (ids, i32),
                    out_shardings=(kv_sh, kv_sh, rep)
                    if mesh is not None else None)
                self._insert_execs[sp] = inference.aot_compile(
                    insert_step,
                    (sspec, i32, kv, kv, lg, i32, i32, i32, b1, f32, i32,
                     i32, i32, i32) + dkv_in,
                    donate_argnums=(0,), out_shardings=outs(rep, rep))
                self.compile_count += 2
                tail = (i32, ids, pvec, i32, i32, i32, i32, b1, f32, i32,
                        i32, i32, i32)
                if self._prefix is not None:
                    self._insert_prefix_execs[sp] = inference.aot_compile(
                        insert_prefix_step,
                        (pspec,) + dpre + (sspec,) + tail,
                        donate_argnums=(1 + len(dpre),),
                        out_shardings=outs(rep, rep))
                    self.compile_count += 1
                if self.prefill_chunk and sp <= chunk_bucket:
                    self._chunk_execs[sp] = inference.aot_compile(
                        chunk_step,
                        (pspec,) + dpre + (sspec,) + tail[:-1]
                        + (i32, i32, b1),
                        donate_argnums=(1 + len(dpre),),
                        out_shardings=outs(rep, rep))
                    self.compile_count += 1
        self.metrics.set_compile_count(self.compile_count)
        logger.info(
            "generation warmup compiled %d executable(s): slots=%d "
            "S_max=%d prompt buckets=%s pages=%dx%d cache=%.1f MB%s",
            self.compile_count, self.max_slots, self.max_seq_len,
            self.prompt_buckets, geom.num_pages, geom.page_size,
            geom.kv_bytes() / 1048576,
            f" mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}"
            if mesh is not None else "")

        # publish introspection surfaces (monitor/perf.py): the decode
        # op table over /debug/perf, and owner tags so the buffer
        # census attributes the KV cache and weights ("latest engine
        # wins" — one process, one serving engine in practice)
        from ..monitor import perf as _perf

        _perf.register_provider("decode", self.op_report)
        _perf.register_owner("params", lambda: self._params)
        _perf.register_owner("kv_pages", lambda: self._state)

        self._started = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle-genserve-decode")
        self._thread.start()
        return self

    def op_report(self, *, measured_step_ms=None, trace_dir=None):
        """Per-op attribution of the AOT-compiled decode step
        (monitor/perf.py).  Measured time defaults to the inter-token
        p50 — in steady state one decode iteration IS the inter-token
        gap.  Reads only the compiled executable's HLO; never touches
        the live (donated) decode state."""
        exe = self._spec_exec if self._spec_exec is not None \
            else self._decode_exec
        if exe is None:
            raise RuntimeError("op_report() before start()")
        ca = exe.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
        if measured_step_ms is None:
            gaps = sorted(self.metrics._gaps)
            if gaps:
                measured_step_ms = gaps[len(gaps) // 2] * 1e3
        from ..monitor import perf as _perf

        return _perf.build_report(exe, name="decode",
                                  cost_analysis=dict(ca),
                                  measured_step_ms=measured_step_ms,
                                  trace_dir=trace_dir)

    # -- request intake ----------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        raise ValueError(
            f"prompt length {n} exceeds the largest prompt bucket "
            f"{self.prompt_buckets[-1]}")

    def submit(self, prompt, max_new_tokens=32, *, do_sample=False,
               temperature=1.0, top_k=0, seed=0, resume_pos=0,
               eos_token_id=None, deadline_ms=None,
               span=None) -> GenerationHandle:
        """Enqueue one prompt (1-D int token ids).  Returns a streaming
        :class:`GenerationHandle`.  Raises QueueFullError under
        backpressure, EngineStoppedError once draining/stopped, and
        ValueError for requests the cache geometry cannot hold.

        `span`: an open request span to hang the engine's gen.queued /
        gen.prefill / gen.decode children from (the HTTP server passes
        its adopted server.generate span); without one, a sampled root
        span is started when the process tracer is enabled."""
        if self._draining or self._stopped:
            self.metrics.count("rejected_draining")
            raise EngineStoppedError("generation engine is draining — no "
                                     "new requests accepted")
        if not self._started:
            raise EngineStoppedError("generation engine not started — "
                                     "call start()")
        prompt = np.array(prompt, np.int32).reshape(-1)
        L = int(prompt.shape[0])
        if L < 1:
            raise ValueError("empty prompt")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        bucket = self._bucket_for(L)
        if L + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt {L} + max_new_tokens {max_new_tokens} exceeds "
                f"max_seq_len {self.max_seq_len}")
        worst_pages = self.geometry.pages_for(L + max_new_tokens)
        if worst_pages > self.geometry.num_pages:
            # could NEVER be admitted: even an empty pool is too small
            self.metrics.count("rejected_pages_exhausted")
            raise ValueError(
                f"request needs {worst_pages} KV pages worst-case; the "
                f"pool holds {self.geometry.num_pages} (raise num_pages "
                f"or page_size)")
        top_k = int(top_k)
        if top_k > self.max_top_k:
            raise ValueError(f"top_k {top_k} exceeds max_top_k "
                             f"{self.max_top_k}")
        resume_pos = int(resume_pos)
        if resume_pos < 0:
            raise ValueError("resume_pos must be >= 0")
        eos = self.geometry.vocab_size if eos_token_id is None \
            else int(eos_token_id)
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        own_span = False
        if span is not None and not span.sampled:
            span = None
        elif span is None:
            tracer = _tracing.default_tracer()
            if tracer.enabled:
                root = tracer.start_span(
                    "genserve.request",
                    attrs={"prompt_len": L,
                           "max_new_tokens": max_new_tokens})
                if root.sampled:
                    span, own_span = root, True
        req = _GenRequest(self, prompt, bucket, max_new_tokens,
                          bool(do_sample), float(temperature), top_k,
                          int(seed), eos, deadline, span=span,
                          own_span=own_span, resume_pos=resume_pos)
        if span is not None:
            # attached BEFORE enqueue: the decode thread may admit the
            # request (and close this child) before put_nowait returns
            req.span_queue = span.child("gen.queued", bucket=bucket)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.count("rejected_queue_full")
            req.end_spans("rejected_queue_full")
            raise QueueFullError(
                f"generation queue at capacity ({self.queue_depth}); "
                "retry with backoff") from None
        self._idle.clear()
        self.metrics.count("admitted")
        return req.handle

    def generate(self, prompt, max_new_tokens=32, timeout=None, **kw):
        """Synchronous convenience: submit + result."""
        return self.submit(prompt, max_new_tokens, **kw).result(timeout)

    # -- the decode loop ---------------------------------------------------
    def _wake(self):
        try:
            self._queue.put_nowait(_WAKE)
        except queue.Full:
            pass

    def _run(self):
        try:
            while True:
                self._pull_requests()
                self._sweep_backlog()
                self._admit_ready()
                self._preempt_swept()
                occupied = self._sched.occupied
                self.metrics.set_occupancy(len(occupied))
                self.metrics.set_page_occupancy(
                    self.geometry.num_pages - self._sched.pages_available)
                if occupied and not self._stopped:
                    # at most ONE prefill chunk per iteration, then a
                    # decode step for the armed lanes — a long prompt
                    # streams in without stalling in-flight streams
                    self._advance_chunk()
                    if len(self._sched.occupied) > self._sched.prefilling():
                        if self._spec_exec is not None:
                            outs, emitted, fin = self.step_spec()
                            self._distribute_spec(outs, emitted, fin)
                        else:
                            toks, fin = self.step()
                            self._distribute(toks, fin)
                    continue
                if self._queue.empty() and not self._backlog:
                    self._idle.set()
                    if self._draining or self._stopped:
                        return
        except BaseException as e:  # pragma: no cover - last-resort:
            # never die silently
            logger.exception("generation decode loop crashed")
            try:
                from ..monitor import perf as _perf

                if _perf.is_oom(e):
                    # the decode thread CAUGHT the failure, so the
                    # crash excepthook will never see it — dump the
                    # census + op table postmortem here
                    _perf.oom_postmortem(e)
            except Exception:  # noqa: BLE001 - never mask the crash
                pass
            self._stopped = True
            self._fail_everything(EngineStoppedError(
                "generation decode loop crashed"))
            self._idle.set()
            raise

    def _pull_requests(self):
        """Move queued requests to the backlog; block only when idle."""
        block = (not self._sched.occupied and not self._backlog
                 and not (self._draining or self._stopped))
        try:
            req = self._queue.get(block=block)
        except queue.Empty:
            return
        if req is not _WAKE:
            self._backlog.append(req)
        while True:
            try:
                r2 = self._queue.get_nowait()
            except queue.Empty:
                return
            if r2 is not _WAKE:
                self._backlog.append(r2)

    def _sweep_backlog(self):
        now = time.monotonic()
        keep = collections.deque()
        for req in self._backlog:
            if req.cancelled:
                self.metrics.count("cancelled")
                req.end_spans("cancelled")
                req.handle._finish()
            elif req.deadline is not None and now > req.deadline:
                self.metrics.count("deadline_expired")
                req.end_spans("deadline_expired")
                req.handle._finish(DeadlineExceededError(
                    "request deadline passed while queued"))
            else:
                keep.append(req)
        self._backlog = keep

    def _admit_ready(self):
        while self._backlog and not self._stopped:
            req = self._backlog[0]
            j_hit, shared = (self._prefix.lookup(req.prompt)
                             if self._prefix is not None else (0, ()))
            need = self.geometry.pages_for(
                len(req.prompt) + req.max_new_tokens) - j_hit
            if not self._sched.can_admit(need):
                # page-pressure escape hatch BEFORE queuing: when a
                # free lane exists and idle prefix-cache residents are
                # what exhausts the pool, evict LRU entries until the
                # head's reservation fits — otherwise a stream of
                # distinct prompts parks one-reader prefixes over the
                # whole pool and the backlog never drains
                if (self._prefix is not None and self._sched.has_free()
                        and len(self._prefix)
                        and need > self._sched.pages_available):
                    short = need - self._sched.pages_available
                    self._reclaim(self._prefix.evict_idle(short))
                    self._sched.set_shared_resident(
                        self._prefix.resident_pages)
                if not self._sched.can_admit(need):
                    # no free lane, or the pool cannot reserve the
                    # worst case even after eviction — FIFO
                    # head-of-line wait until a retirement frees
                    # lanes/pages (admit-and-crash is not an option)
                    break
            self._backlog.popleft()
            slot = self._sched.admit(req, n_pages=need)
            try:
                suffix_len = len(req.prompt) \
                    - j_hit * self.geometry.page_size
                if self.prefill_chunk and suffix_len > self.prefill_chunk:
                    self._admit_chunked(req, slot, j_hit, shared)
                else:
                    self._admit(req, slot, j_hit, shared)
            except Exception as e:  # noqa: BLE001 - fail THIS request,
                # keep the decode loop alive for the others
                logger.exception("generation admission failed")
                self.metrics.count("errors")
                self._host_retire(slot)
                req.end_spans("error")
                req.handle._finish(e)

    def _admit(self, req: _GenRequest, slot: int, j_hit: int, shared):
        """Prefill + insert: map the slot's cache pages (reusing any
        cached prefix pages) and arm the lane with its first sampled
        token — the request joins the in-flight batch at this iteration
        boundary."""
        geom = self.geometry
        L = len(req.prompt)
        if req.span_queue is not None:
            req.span_queue.end(status="ok")
            req.span_queue = None
        j_reg = (self._prefix.shareable_pages(L)
                 if self._prefix is not None else 0)
        pinned = max(j_hit, j_reg)
        sp_prefill = (req.span.child("gen.prefill", bucket=req.bucket,
                                     prompt_len=L, slot=slot,
                                     prefix_pages=j_hit)
                      if req.span is not None else None)
        stop = np.int32(L + req.max_new_tokens)
        dpre = ((self._draft_params,)
                if self.draft_model is not None else ())
        with RecordEvent("paddle.genserve/prefill"):
            if j_hit > 0:
                # prefix hit: prefill ONLY the suffix
                suffix = req.prompt[j_hit * geom.page_size:]
                sb = self._bucket_for(len(suffix))
                ids = np.zeros((1, sb), np.int32)
                ids[0, :len(suffix)] = suffix
                shared_vec = np.full((geom.pages_per_slot,), -1, np.int32)
                shared_vec[:j_hit] = shared[:j_hit]
                state, tok1, row = self._insert_prefix_execs[sb](
                    self._params, *dpre, self._state, np.int32(slot),
                    ids, shared_vec, np.int32(j_hit), np.int32(L),
                    np.int32(req.seed), np.int32(req.resume_pos),
                    np.bool_(req.do_sample),
                    np.float32(req.temperature), np.int32(req.top_k),
                    stop, np.int32(req.eos), np.int32(pinned))
            else:
                ids = np.zeros((1, req.bucket), np.int32)
                ids[0, :L] = req.prompt
                out = self._prefill_execs[req.bucket](
                    self._params, *dpre, ids, np.int32(L))
                k_new, v_new, logits = out[:3]
                state, tok1, row = self._insert_execs[req.bucket](
                    self._state, np.int32(slot), k_new, v_new, logits,
                    np.int32(L), np.int32(req.seed),
                    np.int32(req.resume_pos),
                    np.bool_(req.do_sample), np.float32(req.temperature),
                    np.int32(req.top_k), stop, np.int32(req.eos),
                    np.int32(pinned), *out[3:])
        self._state = state
        with host_fetch():
            t1 = int(np.array(tok1, copy=True))
            row_np = np.array(row, copy=True)
        if self._prefix is not None:
            self.metrics.count_prefix(hit=j_hit > 0)
            pin_pages = [int(p) for p in row_np[:pinned]]
            self._prefix.pin(pin_pages)
            self._slot_pins[slot] = pin_pages
            self._reclaim(self._prefix.register(req.prompt, row_np,
                                                j_hit, j_reg))
            self._sched.set_shared_resident(self._prefix.resident_pages)
        if sp_prefill is not None:
            sp_prefill.end(status="ok")
        now = time.monotonic()
        req.t_last_token = now
        req.handle._push(t1)
        if req.span is not None:
            req.span.event("first_token", slot=slot)
        self.metrics.observe_ttft(now - req.handle.t_submit)
        self.metrics.observe_tokens(1)
        if req.max_new_tokens == 1 or t1 == req.eos:
            self._release([slot])
            self._host_retire(slot)
            self.metrics.count("retired")
            req.end_spans("ok")
            req.handle._finish()
        elif req.span is not None:
            req.span_decode = req.span.child("gen.decode", slot=slot)

    def _admit_chunked(self, req: _GenRequest, slot: int, j_hit: int,
                       shared):
        """Admit a long prompt WITHOUT prefilling it: the slot occupies
        the scheduler (worst-case pages reserved up front) while
        ``_advance_chunk`` streams ``prefill_chunk``-token slices into
        its pages, one per decode iteration.  Only the final chunk arms
        the lane."""
        geom = self.geometry
        L = len(req.prompt)
        if req.span_queue is not None:
            req.span_queue.end(status="ok")
            req.span_queue = None
        j_reg = (self._prefix.shareable_pages(L)
                 if self._prefix is not None else 0)
        req.j_hit = j_hit
        req.pin_final = max(j_hit, j_reg)
        req.prefilling = True
        req.prefill_cursor = j_hit * geom.page_size
        row = np.full((geom.pages_per_slot,), -1, np.int32)
        if j_hit > 0:
            row[:j_hit] = shared[:j_hit]
        req.chunk_row = row
        if self._prefix is not None:
            self.metrics.count_prefix(hit=j_hit > 0)
            # pin the cache-shared head NOW: it must stay resident for
            # every later chunk's prefix gather (LRU cannot evict it)
            pin_pages = [int(p) for p in row[:j_hit]]
            self._prefix.pin(pin_pages)
            self._slot_pins[slot] = pin_pages
            self._sched.set_shared_resident(self._prefix.resident_pages)
        if req.span is not None:
            req.span_decode = req.span.child(
                "gen.prefill", bucket=req.bucket, prompt_len=L,
                slot=slot, prefix_pages=j_hit, chunked=True)

    def _advance_chunk(self):
        """Advance ONE prefilling slot by one chunk — bounded work per
        decode iteration, so armed lanes' inter-token gap stays flat
        while a long prompt streams in."""
        if not self.prefill_chunk:
            return
        slot = req = None
        for s, r in self._sched.occupied.items():
            if r.prefilling:
                slot, req = s, r
                break
        if req is None:
            return
        geom = self.geometry
        L = len(req.prompt)
        cur = req.prefill_cursor
        end = min(cur + self.prefill_chunk, L)
        arm = end >= L
        chunk = req.prompt[cur:end]
        sb = self._bucket_for(len(chunk))
        ids = np.zeros((1, sb), np.int32)
        ids[0, :len(chunk)] = chunk
        shared_vec = np.array(req.chunk_row, np.int32)
        dpre = ((self._draft_params,)
                if self.draft_model is not None else ())
        with RecordEvent("paddle.genserve/prefill_chunk"):
            state, tok1, row = self._chunk_execs[sb](
                self._params, *dpre, self._state, np.int32(slot), ids,
                shared_vec, np.int32(cur // geom.page_size),
                np.int32(end), np.int32(req.seed),
                np.int32(req.resume_pos),
                np.bool_(req.do_sample), np.float32(req.temperature),
                np.int32(req.top_k),
                np.int32(L + req.max_new_tokens), np.int32(req.eos),
                np.int32(req.j_hit), np.int32(req.pin_final),
                np.bool_(arm))
        self._state = state
        with host_fetch():
            t1 = int(np.array(tok1, copy=True))
            row_np = np.array(row, copy=True)
        req.chunk_row = row_np
        req.prefill_cursor = end
        self.metrics.count_chunk()
        if req.span_decode is not None:
            req.span_decode.event("chunk", end=end)
        if arm:
            self._arm_chunked(req, slot, row_np, t1)

    def _arm_chunked(self, req: _GenRequest, slot: int, row_np, t1: int):
        """Final chunk ran: register the prompt's shareable prefix,
        deliver the first token, and hand the lane to the decode step
        (or retire immediately on eos / max_new_tokens == 1)."""
        req.prefilling = False
        j_hit = req.j_hit
        if self._prefix is not None:
            j_reg = self._prefix.shareable_pages(len(req.prompt))
            pin_pages = [int(p) for p in row_np[:req.pin_final]]
            # the cache-hit head was pinned at admission; pin the
            # freshly registered tail
            self._prefix.pin(pin_pages[j_hit:])
            self._slot_pins[slot] = pin_pages
            self._reclaim(self._prefix.register(req.prompt, row_np,
                                                j_hit, j_reg))
            self._sched.set_shared_resident(self._prefix.resident_pages)
        if req.span_decode is not None:
            req.span_decode.end(status="ok")
            req.span_decode = None
        now = time.monotonic()
        req.t_last_token = now
        req.handle._push(t1)
        if req.span is not None:
            req.span.event("first_token", slot=slot)
        self.metrics.observe_ttft(now - req.handle.t_submit)
        self.metrics.observe_tokens(1)
        if req.max_new_tokens == 1 or t1 == req.eos:
            self._release([slot])
            self._host_retire(slot)
            self.metrics.count("retired")
            req.end_spans("ok")
            req.handle._finish()
        elif req.span is not None:
            req.span_decode = req.span.child("gen.decode", slot=slot)

    def _release(self, slots):
        mask = np.zeros((self.max_slots,), np.bool_)
        for s in slots:
            mask[s] = True
        self._state = self._release_exec(self._state, mask)

    def _host_retire(self, slot: int):
        """Host-side retirement: drop the slot's scheduler reservation
        and its prefix-cache pins, reclaiming shared pages whose
        refcount hit zero.  The device-side page free happened in-graph
        (decode/release).  Returns the slot's request."""
        req = self._sched.retire(slot)
        pages = self._slot_pins.pop(slot, None)
        if pages and self._prefix is not None:
            self._reclaim(self._prefix.unpin(pages))
        if self._prefix is not None:
            self._sched.set_shared_resident(self._prefix.resident_pages)
        return req

    def _reclaim(self, pages):
        """Return evicted/orphaned prefix-cache pages to the device free
        stack (chunked through the fixed-width reclaim executable)."""
        if not pages:
            return
        pps = self.geometry.pages_per_slot
        for i in range(0, len(pages), pps):
            vec = np.full((pps,), -1, np.int32)
            chunk = pages[i:i + pps]
            vec[:len(chunk)] = chunk
            self._state = self._reclaim_exec(self._state, vec)

    def _preempt_swept(self):
        swept = self._sched.sweep()
        if not swept:
            return
        self._release([slot for slot, _, _ in swept])
        for slot, req, reason in swept:
            self._host_retire(slot)
            self.metrics.count(reason)
            self.metrics.count("preempted")
            req.end_spans(reason)
            req.handle._finish(
                None if reason == "cancelled" else DeadlineExceededError(
                    "request deadline passed mid-decode"))

    def step(self):
        """ONE decode iteration: every in-flight lane advances a token.
        The state pytree is donated to the compiled executable (the KV
        page pool is rewritten on device, never fetched); only the
        sampled token ids and finished mask cross to host, under
        host_fetch()."""
        self._iter += 1
        chaos.on_step(self._iter)   # fault-injection seam (utils/chaos)
        with RecordEvent("paddle.genserve/decode"):
            state, toks, fin = self._decode_exec(self._params, self._state)
        self._state = state
        with host_fetch():
            toks_np = np.array(toks, copy=True)
            fin_np = np.array(fin, copy=True)
        return toks_np, fin_np

    def step_spec(self):
        """ONE speculative iteration (draft chain + batched target
        verify, compiled as a single executable): every armed lane
        advances 1..spec_tokens+1 tokens.  Returns (outs [slots, K+1],
        emitted [slots, K+1] prefix mask, finished [slots])."""
        self._iter += 1
        chaos.on_step(self._iter)
        with RecordEvent("paddle.genserve/spec_decode"):
            state, outs, emitted, fin = self._spec_exec(
                self._params, self._draft_params, self._state)
        self._state = state
        with host_fetch():
            outs_np = np.array(outs, copy=True)
            emitted_np = np.array(emitted, copy=True)
            fin_np = np.array(fin, copy=True)
        return outs_np, emitted_np, fin_np

    def _distribute_spec(self, outs_np, emitted_np, fin_np):
        now = time.monotonic()
        emitted_total = accepted = proposed = 0
        for slot, req in list(self._sched.occupied.items()):
            if req.prefilling:
                continue
            n = int(emitted_np[slot].sum())
            if n <= 0:
                continue
            emitted_total += n
            if not req.do_sample:
                # n - 1 of this run's tokens came from accepted draft
                # proposals (the last one is the target's own next
                # token, free either way)
                accepted += n - 1
                proposed += self.spec_tokens
            gap = ((now - req.t_last_token) / n
                   if req.t_last_token is not None else None)
            for i in range(n):
                if gap is not None:
                    self.metrics.observe_inter_token(gap)
                req.handle._push(int(outs_np[slot, i]))
                if req.span_decode is not None:
                    req.span_decode.event("token",
                                          i=len(req.handle.tokens))
            req.t_last_token = now
            if bool(fin_np[slot]):
                self._host_retire(slot)
                self.metrics.count("retired")
                req.end_spans("ok")
                req.handle._finish()
        self.metrics.observe_tokens(emitted_total)
        if proposed:
            self.metrics.observe_spec(accepted, proposed)

    def _distribute(self, toks_np, fin_np):
        now = time.monotonic()
        occupied = [(s, r) for s, r in self._sched.occupied.items()
                    if not r.prefilling]
        self.metrics.observe_tokens(len(occupied))
        for slot, req in occupied:
            tok = int(toks_np[slot])
            if req.t_last_token is not None:
                self.metrics.observe_inter_token(now - req.t_last_token)
            req.t_last_token = now
            req.handle._push(tok)
            if req.span_decode is not None:
                # host ints only — toks/fin were fetched in step()
                req.span_decode.event("token", i=len(req.handle.tokens))
            if bool(fin_np[slot]):
                # the decode step already pushed the lane's private
                # pages back in-graph; this drops the host bookkeeping
                self._host_retire(slot)
                self.metrics.count("retired")
                req.end_spans("ok")
                req.handle._finish()

    def _fail_everything(self, exc):
        for dq in (self._backlog,):
            while dq:
                req = dq.popleft()
                req.end_spans("error")
                req.handle._finish(exc)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not _WAKE:
                req.end_spans("error")
                req.handle._finish(exc)
        for slot in list(self._sched.occupied):
            req = self._sched.retire(slot)
            self._slot_pins.pop(slot, None)
            req.end_spans("error")
            req.handle._finish(exc)

    # -- shutdown ----------------------------------------------------------
    def drain(self, timeout=None) -> bool:
        """Graceful: reject new work, finish every queued and in-flight
        generation, stop the decode loop.  True when fully drained."""
        self._draining = True
        if self._thread is None:
            return True
        self._wake()
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        drained = self._idle.wait(timeout)
        self._thread.join(None if deadline is None
                          else max(0.0, deadline - time.monotonic()))
        alive = self._thread.is_alive()
        if not alive:
            self._thread = None
        # a submit racing the drain flag can slip a request in after the
        # loop's final empty-check — fail it, never strand its handle
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is _WAKE:
                continue
            drained = False
            if not req.handle.done:
                req.end_spans("rejected_draining")
                req.handle._finish(EngineStoppedError(
                    "request arrived during drain"))
        return drained and not alive

    def stop(self):
        """Hard stop: fail everything queued and in-flight."""
        self._stopped = True
        self._draining = True
        thread = self._thread
        if thread is not None:
            self._wake()
            thread.join(5.0)
            if not thread.is_alive():
                self._thread = None
        self._fail_everything(EngineStoppedError("engine stopped"))

    @property
    def draining(self) -> bool:
        return self._draining

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        if exc[0] is None:
            self.drain(timeout=30.0)
        self.stop()
        return False


def main(argv=None):
    """Standalone generation server over a randomly initialized GPT —
    the tools/serve_smoke.sh concurrent-decode fixture (real deployments
    build a GenerationEngine around trained weights, or call
    ``Model.serve_generate()``)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="paddle_tpu generation server (continuous-batching "
                    "decode with a device-resident paged KV cache)")
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--heads", type=int, default=4)
    parser.add_argument("--vocab", type=int, default=211)
    parser.add_argument("--max-seq-len", type=int, default=64)
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--prompt-buckets", default="8,16")
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--num-pages", type=int, default=0,
                        help="KV page pool size; 0 = dense-equivalent "
                             "(slots * pages_per_slot)")
    parser.add_argument("--prefix-cache", type=int, default=1,
                        help="1 shares identical prompt prefixes as "
                             "read-only pages; 0 disables")
    parser.add_argument("--draft-layers", type=int, default=0,
                        help="layers of the speculative draft model; "
                             "0 disables speculative decode")
    parser.add_argument("--spec-tokens", type=int, default=4,
                        help="draft proposals per speculative iteration")
    parser.add_argument("--prefill-chunk", type=int, default=0,
                        help="tokens per prefill chunk (multiple of "
                             "page-size); 0 prefills whole prompts")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8867,
                        help="0 picks a free port (printed on stdout)")
    args = parser.parse_args(argv)

    import logging as _logging

    _logging.basicConfig(level=_logging.INFO)
    import paddle_tpu as paddle
    from ..models.gpt import GPTConfig, GPTForCausalLM
    from .server import ServingServer

    paddle.seed(args.seed)
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    max_position_embeddings=args.max_seq_len,
                    dropout=0.0, attn_dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    draft = None
    if args.draft_layers > 0:
        dcfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                         num_layers=args.draft_layers,
                         num_heads=args.heads,
                         max_position_embeddings=args.max_seq_len,
                         dropout=0.0, attn_dropout=0.0)
        draft = GPTForCausalLM(dcfg)
        # seed the draft from the target's first layers + embeddings so
        # the random-weight smoke still accepts some proposals
        tgt = dict(model.state_dict())
        dsd = draft.state_dict()
        for name in list(dsd):
            if name in tgt and tuple(dsd[name].shape) \
                    == tuple(tgt[name].shape):
                dsd[name] = tgt[name]
        draft.set_state_dict(dsd)
        draft.eval()
    engine = GenerationEngine(model, max_slots=args.slots,
                              max_seq_len=args.max_seq_len,
                              prompt_buckets=args.prompt_buckets,
                              page_size=args.page_size,
                              num_pages=args.num_pages,
                              prefix_cache=bool(args.prefix_cache),
                              draft_model=draft,
                              spec_tokens=args.spec_tokens,
                              prefill_chunk=args.prefill_chunk)
    server = ServingServer(None, gen_engine=engine, host=args.host,
                           port=args.port).start()
    # parse-friendly readiness line (tools/serve_smoke.sh greps it)
    print(f"paddle_tpu.serving listening on {server.url}", flush=True)

    # elastic fleet membership: when launched under a replica supervisor
    # (serving/fleet.py exports PADDLE_POD_COORD + PADDLE_POD_RANK) the
    # replica registers its URL in the coordinator KV and heartbeats so
    # the router evicts it on the epoch delta — faster than its probe
    # timeout — when it dies or partitions.  A REPLICA_PARTITION chaos
    # drill silences the heartbeats while the HTTP server keeps serving.
    from ..distributed.podcoord import PodClient

    pod = PodClient.from_env()
    if pod is not None:
        from ..utils import chaos as _chaos

        pod.kv_set(f"serving/replica/{pod.rank}/url",
                   server.url.encode("utf-8"))
        pod.start_heartbeats()
        _chaos.register_partition_hook(pod.stop_heartbeats)
        logger.info("replica rank %d registered with fleet coordinator",
                    pod.rank)
    return server.wait()


if __name__ == "__main__":
    import sys

    sys.exit(main())
