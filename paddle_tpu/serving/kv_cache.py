"""Device-resident slot-batched KV cache for continuous-batching decode.

The generation engine's whole mutable decode state is ONE pytree of
fixed-shape jax arrays — the stacked per-layer KV cache
(``[layers, slots, S_max, nh, hd]``, the fused_multi_transformer CacheKV
layout turned TPU-native) plus the per-slot lane registers (pending
token, write position, active mask, sampling params, per-slot PRNG
keys).  Every jitted transition (insert / decode / release) takes the
state as its first argument with ``donate_argnums=(0,)`` — the
TrainEngine donation contract from hapi/engine.py — so XLA rewrites the
cache in place and the KV bytes NEVER round-trip to host between
iterations.  The engine thread owns the single live reference; a
consumed (donated) state is immediately replaced by the transition's
output.

This module is layout + traced transitions only; scheduling policy lives
in serving/scheduler.py and the compiled-executable lifecycle in
serving/generation.py.
"""
from __future__ import annotations

from dataclasses import dataclass

__all__ = ["CacheGeometry", "make_state", "state_specs", "write_prompt",
           "admit_slot", "release_slots"]


@dataclass(frozen=True)
class CacheGeometry:
    """Static shape of the decode state — one geometry == one decode
    executable (the zero-steady-state-compile invariant)."""
    num_layers: int
    max_slots: int
    max_seq_len: int       # S_max: prompt + generated tokens per slot
    num_heads: int
    head_dim: int
    vocab_size: int
    dtype: str = "float32"

    @property
    def kv_shape(self):
        return (self.num_layers, self.max_slots, self.max_seq_len,
                self.num_heads, self.head_dim)

    def kv_bytes(self) -> int:
        import numpy as np

        n = 2  # k and v
        for d in self.kv_shape:
            n *= d
        return n * np.dtype(self.dtype).itemsize


def make_state(geom: CacheGeometry):
    """Fresh all-lanes-free decode state (device arrays).

    Keys: ``k``/``v`` the stacked cache; per-slot lanes ``tok`` (pending
    token, written at ``pos`` next iteration), ``pos`` (absolute write
    index), ``active``, ``rng`` (per-slot PRNG key), and the per-slot
    sampling registers ``do_sample``/``temp``/``top_k``/``eos``/
    ``stop_pos`` (stop_pos = prompt_len + max_new_tokens; a lane retires
    when its next write position would reach it, or on eos).
    """
    import jax
    import jax.numpy as jnp

    S = geom.max_slots
    key_shape = jax.random.PRNGKey(0).shape  # (2,) for threefry
    return {
        "k": jnp.zeros(geom.kv_shape, jnp.dtype(geom.dtype)),
        "v": jnp.zeros(geom.kv_shape, jnp.dtype(geom.dtype)),
        "tok": jnp.zeros((S,), jnp.int32),
        "pos": jnp.zeros((S,), jnp.int32),
        "active": jnp.zeros((S,), bool),
        "rng": jnp.zeros((S,) + tuple(key_shape), jnp.uint32),
        "do_sample": jnp.zeros((S,), bool),
        "temp": jnp.ones((S,), jnp.float32),
        "top_k": jnp.zeros((S,), jnp.int32),
        "eos": jnp.full((S,), geom.vocab_size, jnp.int32),  # V = never
        "stop_pos": jnp.zeros((S,), jnp.int32),
    }


def state_specs(state):
    """ShapeDtypeStructs mirroring a state pytree (AOT lowering input)."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state)


def write_prompt(state, slot, k_new, v_new):
    """Scatter one request's prefill K/V (``[layers, Sp, nh, hd]``) into
    cache row ``slot``, zero-filling positions Sp..S_max-1 (clears the
    previous occupant's tail — slot-reuse isolation by construction, not
    just by masking).  Traced; ``slot`` is a traced scalar so ONE
    executable per prompt bucket serves every slot index."""
    import jax.numpy as jnp
    from jax import lax

    k_cache = state["k"]
    L, _, S_max = k_cache.shape[0], k_cache.shape[1], k_cache.shape[2]
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.int32(0)

    def pad(x):
        full = jnp.zeros((L, S_max) + x.shape[2:], k_cache.dtype)
        return full.at[:, :x.shape[1]].set(x.astype(k_cache.dtype))

    k_cache = lax.dynamic_update_slice(
        k_cache, pad(k_new)[:, None], (zero, slot, zero, zero, zero))
    v_cache = lax.dynamic_update_slice(
        state["v"], pad(v_new)[:, None], (zero, slot, zero, zero, zero))
    return dict(state, k=k_cache, v=v_cache)


def admit_slot(state, slot, tok, length, rng_key, do_sample, temp, top_k,
               stop_pos, eos):
    """Arm lane ``slot``: pending token ``tok`` (the first generated
    token, sampled from the prefill logits) will be written at position
    ``length`` on the next decode iteration.  Traced scalar args."""
    import jax.numpy as jnp

    slot = jnp.asarray(slot, jnp.int32)
    return dict(
        state,
        tok=state["tok"].at[slot].set(jnp.asarray(tok, jnp.int32)),
        pos=state["pos"].at[slot].set(jnp.asarray(length, jnp.int32)),
        active=state["active"].at[slot].set(True),
        rng=state["rng"].at[slot].set(rng_key),
        do_sample=state["do_sample"].at[slot].set(
            jnp.asarray(do_sample, bool)),
        temp=state["temp"].at[slot].set(jnp.asarray(temp, jnp.float32)),
        top_k=state["top_k"].at[slot].set(jnp.asarray(top_k, jnp.int32)),
        stop_pos=state["stop_pos"].at[slot].set(
            jnp.asarray(stop_pos, jnp.int32)),
        eos=state["eos"].at[slot].set(jnp.asarray(eos, jnp.int32)),
    )


def release_slots(state, mask):
    """Deactivate the masked lanes (retire / cancel / deadline-preempt).
    The cache rows keep their bytes — the next occupant's write_prompt
    overwrites them and the position mask hides them meanwhile."""
    return dict(state, active=state["active"] & ~mask)
