"""Device-resident PAGED KV cache for continuous-batching decode.

The generation engine's whole mutable decode state is ONE pytree of
fixed-shape jax arrays: a page pool ``[layers, num_pages, page_size,
nh, hd]`` (the fused_multi_transformer CacheKV layout broken into
fixed-size pages, vLLM-style), an int32 per-slot page table
``[max_slots, pages_per_slot]`` (-1 = unmapped), a free-list register
(``free_stack`` + scalar ``free_count``), and the per-slot lane
registers (pending token, write position, active mask, sampling params,
per-slot PRNG keys, pinned shared-page count).

Every jitted transition (insert / decode / release / reclaim) takes the
state as its first state-argument with ``donate_argnums`` — the
TrainEngine donation contract from hapi/engine.py — so XLA rewrites the
pool in place and the KV bytes NEVER round-trip to host.  Page
allocation happens IN-GRAPH: admission maps ``ceil(len/page_size)``
pages off the free stack, decode pops a fresh tail page the iteration a
lane's write position crosses a page boundary, and retirement pushes a
lane's private pages back — so cache HBM is set by actual token
footprint (``num_pages``), not ``max_slots * S_max`` worst case.

Pages with table index below a lane's ``pinned`` register are SHARED
(prefix-cache pages, serving/prefix_cache.py): the device never frees
them; the host returns them through ``reclaim_pages`` once their
refcount drops to zero.  The free-list discipline assumes the host
admits only requests whose worst-case page demand is reserved
(serving/scheduler.py) — ``take_pages`` underflows silently otherwise.

This module is layout + traced transitions only; scheduling policy lives
in serving/scheduler.py and the compiled-executable lifecycle in
serving/generation.py.
"""
from __future__ import annotations

from dataclasses import dataclass

__all__ = ["CacheGeometry", "make_state", "state_specs", "take_pages",
           "push_pages", "write_prompt", "admit_slot", "release_slots",
           "reclaim_pages"]


@dataclass(frozen=True)
class CacheGeometry:
    """Static shape of the decode state — one geometry == one decode
    executable (the zero-steady-state-compile invariant).

    ``num_pages`` bounds cache HBM: 0 (the default) sizes the pool
    dense-equivalently at ``max_slots * pages_per_slot`` so every slot
    can always hold S_max tokens; smaller pools oversubscribe slots
    against actual footprint (the scheduler queues admissions that
    cannot reserve their worst case)."""
    num_layers: int
    max_slots: int
    max_seq_len: int       # S_max: prompt + generated tokens per slot
    num_heads: int
    head_dim: int
    vocab_size: int
    page_size: int = 16
    num_pages: int = 0     # 0 = max_slots * pages_per_slot
    dtype: str = "float32"
    # speculative decode: the draft model's KV lives in a parallel pool
    # indirected through the SAME page table (one allocation decision
    # covers both models); 0 layers = no draft pool in the state
    draft_layers: int = 0
    draft_num_heads: int = 0
    draft_head_dim: int = 0

    def __post_init__(self):
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        if self.num_pages == 0:
            object.__setattr__(self, "num_pages",
                               self.max_slots * self.pages_per_slot)
        if self.num_pages < 1:
            raise ValueError(
                f"num_pages must be >= 1, got {self.num_pages}")

    @property
    def pages_per_slot(self) -> int:
        return -(-self.max_seq_len // self.page_size)

    @property
    def pool_shape(self):
        return (self.num_layers, self.num_pages, self.page_size,
                self.num_heads, self.head_dim)

    @property
    def draft_pool_shape(self):
        return (self.draft_layers, self.num_pages, self.page_size,
                self.draft_num_heads, self.draft_head_dim)

    def page_bytes(self) -> int:
        """Bytes ONE page costs across k+v and all layers (draft pool
        included when speculative) — the HBM sizing unit: cache bytes =
        num_pages * page_bytes()."""
        import numpy as np

        per_tok = (self.num_layers * self.num_heads * self.head_dim
                   + self.draft_layers * self.draft_num_heads
                   * self.draft_head_dim)
        return (2 * self.page_size * per_tok
                * np.dtype(self.dtype).itemsize)

    def kv_bytes(self) -> int:
        return self.num_pages * self.page_bytes()

    def pages_for(self, n_tokens: int) -> int:
        """Pages an ``n_tokens``-long sequence occupies."""
        return -(-int(n_tokens) // self.page_size)


def make_state(geom: CacheGeometry):
    """Fresh all-pages-free decode state (device arrays).

    Keys: ``kp``/``vp`` the page pools; ``ptab`` the per-slot page
    table (-1 = unmapped); ``free_stack``/``free_count`` the free-list
    register (free page ids live at ``free_stack[:free_count]``, popped
    from the top); per-slot lanes ``tok`` (pending token, written at
    ``pos`` next iteration), ``pos`` (absolute write index), ``active``,
    ``rng`` (per-slot PRNG key), ``pinned`` (table indices below it are
    shared prefix pages the device must not free), and the per-slot
    sampling registers ``do_sample``/``temp``/``top_k``/``eos``/
    ``stop_pos`` (stop_pos = prompt_len + max_new_tokens; a lane retires
    when its next write position would reach it, or on eos).
    """
    import jax
    import jax.numpy as jnp

    S = geom.max_slots
    key_shape = jax.random.PRNGKey(0).shape  # (2,) for threefry
    state = {
        "kp": jnp.zeros(geom.pool_shape, jnp.dtype(geom.dtype)),
        "vp": jnp.zeros(geom.pool_shape, jnp.dtype(geom.dtype)),
        "ptab": jnp.full((S, geom.pages_per_slot), -1, jnp.int32),
        "free_stack": jnp.arange(geom.num_pages, dtype=jnp.int32),
        "free_count": jnp.int32(geom.num_pages),
        "pinned": jnp.zeros((S,), jnp.int32),
        "tok": jnp.zeros((S,), jnp.int32),
        "pos": jnp.zeros((S,), jnp.int32),
        "active": jnp.zeros((S,), bool),
        "rng": jnp.zeros((S,) + tuple(key_shape), jnp.uint32),
        "do_sample": jnp.zeros((S,), bool),
        "temp": jnp.ones((S,), jnp.float32),
        "top_k": jnp.zeros((S,), jnp.int32),
        "eos": jnp.full((S,), geom.vocab_size, jnp.int32),  # V = never
        "stop_pos": jnp.zeros((S,), jnp.int32),
    }
    if geom.draft_layers:
        # draft-model KV pool, same page ids as kp/vp: one page-table
        # row addresses both models' cache for a lane
        state["dkp"] = jnp.zeros(geom.draft_pool_shape,
                                 jnp.dtype(geom.dtype))
        state["dvp"] = jnp.zeros(geom.draft_pool_shape,
                                 jnp.dtype(geom.dtype))
    return state


def state_specs(state, shardings=None):
    """ShapeDtypeStructs mirroring a state pytree (AOT lowering input).
    ``shardings``: optional matching pytree of NamedShardings — attached
    so the layout-aware engine lowers its executables with the page
    pool's head axis pinned over tp."""
    import jax

    if shardings is None:
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state)
    return jax.tree_util.tree_map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        state, shardings)


# -- in-graph free-list register ops ----------------------------------------

def take_pages(free_stack, free_count, need):
    """Pop one page per True lane of ``need`` off the free stack.
    Returns (pages, free_count') — lanes with need=False get -1.  The
    stack array itself is untouched (entries above free_count are
    stale); the host guarantees free_count never underflows by
    reserving worst-case demand at admission."""
    import jax.numpy as jnp

    need = need.astype(bool)
    ranks = jnp.cumsum(need.astype(jnp.int32)) - 1
    idx = jnp.clip(free_count - 1 - ranks, 0, free_stack.shape[0] - 1)
    pages = jnp.where(need, free_stack[idx], -1)
    return pages, free_count - need.sum(dtype=jnp.int32)


def push_pages(free_stack, free_count, pages):
    """Push the valid (>= 0) entries of ``pages`` onto the free stack;
    -1 entries are skipped.  Returns (free_stack', free_count')."""
    import jax.numpy as jnp

    valid = pages >= 0
    ranks = jnp.cumsum(valid.astype(jnp.int32)) - 1
    # invalid entries target one-past-the-end and are dropped
    idx = jnp.where(valid, free_count + ranks, free_stack.shape[0])
    free_stack = free_stack.at[idx].set(pages, mode="drop")
    return free_stack, free_count + valid.sum(dtype=jnp.int32)


# -- traced transitions ------------------------------------------------------

def write_prompt(state, slot, k_new, v_new, length, shared_ids, shared_n,
                 dk_new=None, dv_new=None):
    """Map + fill one admitted request's cache pages.

    ``k_new``/``v_new`` ``[layers, Sb, nh, hd]`` hold prefill K/V for
    absolute positions ``[shared_n * page_size, shared_n * page_size +
    Sb)`` (a full-prompt bucket on a prefix miss, the suffix bucket on a
    prefix hit — full-page-only sharing keeps the boundary aligned).
    Pages ``[0, shared_n)`` of the slot's table row come from
    ``shared_ids`` (already resident read-only prefix pages); pages
    ``[shared_n, ceil(length / page_size))`` are popped off the free
    stack and written — so insert costs O(prompt_len) pages, never
    O(S_max).  Traced; ``slot``/``length``/``shared_n`` are traced
    scalars so ONE executable per bucket serves every slot and every
    prefix split.  Returns ``(state, row)`` — the row is fetched by the
    engine to register/refcount pages host-side.

    ``dk_new``/``dv_new`` (speculative engines only): the DRAFT model's
    prefill K/V for the same positions, scattered into ``dkp``/``dvp``
    at the same page ids — the shared table row keeps both pools'
    extents in lockstep."""
    import jax.numpy as jnp

    kp, vp = state["kp"], state["vp"]
    L, num_pages, ps = kp.shape[0], kp.shape[1], kp.shape[2]
    pps = state["ptab"].shape[1]
    Sb = k_new.shape[1]
    n_pb = -(-Sb // ps)                     # static: pages k_new spans
    slot = jnp.asarray(slot, jnp.int32)
    length = jnp.asarray(length, jnp.int32)
    shared_n = jnp.asarray(shared_n, jnp.int32)

    n_total = (length + ps - 1) // ps       # traced: pages the prompt needs
    j = jnp.arange(pps, dtype=jnp.int32)
    priv = (j >= shared_n) & (j < n_total)
    pages, free_count = take_pages(state["free_stack"],
                                   state["free_count"], priv)
    row = jnp.where(j < shared_n, shared_ids, pages)

    # scatter k_new's page view into the freshly mapped private pages;
    # chunk t covers table index shared_n + t, chunks past the prompt's
    # last page target one-past-the-pool and are dropped
    t = jnp.arange(n_pb, dtype=jnp.int32)
    pj = shared_n + t
    tgt = jnp.where(pj < n_total,
                    row[jnp.clip(pj, 0, pps - 1)], num_pages)

    def to_pages(x, n_layers):
        pad = jnp.zeros((n_layers, n_pb * ps) + x.shape[2:], kp.dtype)
        pad = pad.at[:, :Sb].set(x.astype(kp.dtype))
        return pad.reshape((n_layers, n_pb, ps) + x.shape[2:])

    kp = kp.at[:, tgt].set(to_pages(k_new, L), mode="drop")
    vp = vp.at[:, tgt].set(to_pages(v_new, L), mode="drop")
    ptab = state["ptab"].at[slot].set(row)
    state = dict(state, kp=kp, vp=vp, ptab=ptab, free_count=free_count)
    if dk_new is not None:
        dL = state["dkp"].shape[0]
        dkp = state["dkp"].at[:, tgt].set(to_pages(dk_new, dL),
                                          mode="drop")
        dvp = state["dvp"].at[:, tgt].set(to_pages(dv_new, dL),
                                          mode="drop")
        state = dict(state, dkp=dkp, dvp=dvp)
    return state, row


def admit_slot(state, slot, tok, length, rng_key, do_sample, temp, top_k,
               stop_pos, eos, pinned, active=True):
    """Arm lane ``slot``: pending token ``tok`` (the first generated
    token, sampled from the prefill logits) will be written at position
    ``length`` on the next decode iteration; table indices below
    ``pinned`` are shared prefix pages the device never frees.  Traced
    scalar args.  ``active`` (traced bool) lets chunked prefill run the
    same executable for every chunk while only the FINAL chunk arms the
    lane — earlier chunks keep it parked with the registers staged."""
    import jax.numpy as jnp

    slot = jnp.asarray(slot, jnp.int32)
    return dict(
        state,
        tok=state["tok"].at[slot].set(jnp.asarray(tok, jnp.int32)),
        pos=state["pos"].at[slot].set(jnp.asarray(length, jnp.int32)),
        active=state["active"].at[slot].set(jnp.asarray(active, bool)),
        rng=state["rng"].at[slot].set(rng_key),
        pinned=state["pinned"].at[slot].set(
            jnp.asarray(pinned, jnp.int32)),
        do_sample=state["do_sample"].at[slot].set(
            jnp.asarray(do_sample, bool)),
        temp=state["temp"].at[slot].set(jnp.asarray(temp, jnp.float32)),
        top_k=state["top_k"].at[slot].set(jnp.asarray(top_k, jnp.int32)),
        stop_pos=state["stop_pos"].at[slot].set(
            jnp.asarray(stop_pos, jnp.int32)),
        eos=state["eos"].at[slot].set(jnp.asarray(eos, jnp.int32)),
    )


def release_slots(state, mask):
    """Deactivate the masked lanes (retire / cancel / deadline-preempt)
    and push their PRIVATE pages (table index >= the lane's ``pinned``
    register) back onto the free stack; shared prefix pages stay
    resident for the prefix cache, returned later via
    ``reclaim_pages`` when their host refcount drops to zero."""
    import jax.numpy as jnp

    ptab = state["ptab"]
    col = jnp.arange(ptab.shape[1], dtype=jnp.int32)[None, :]
    freeable = mask[:, None] & (ptab >= 0) & (col >= state["pinned"][:, None])
    free_stack, free_count = push_pages(
        state["free_stack"], state["free_count"],
        jnp.where(freeable, ptab, -1).reshape(-1))
    ptab = jnp.where(mask[:, None], -1, ptab)
    return dict(state, ptab=ptab, free_stack=free_stack,
                free_count=free_count, active=state["active"] & ~mask)


def reclaim_pages(state, pages):
    """Return evicted prefix-cache pages (int32, -1-padded) to the free
    stack — the host calls this once a shared page's refcount hits zero
    (entry evicted AND no slot still reading it)."""
    free_stack, free_count = push_pages(
        state["free_stack"], state["free_count"], pages)
    return dict(state, free_stack=free_stack, free_count=free_count)
