"""Serving metrics: counters, histograms, and a Prometheus text endpoint.

Built on the shared, dependency-free registry in `utils/metrics.py`
(counters/gauges/histograms/reservoir quantiles, one lock, Prometheus
text exposition) — `Histogram` is re-exported from there unchanged, and
`ServingMetrics` is now a declaration of serving's metric catalog over a
private `MetricsRegistry` instance (private so multiple engines in one
process don't collide).  The exposition output is BYTE-IDENTICAL to the
pre-registry module — tests/test_monitor.py pins the golden text.

Quantiles (p50/p99) come from a bounded reservoir of recent request
latencies rather than histogram interpolation, so a smoke test scraping
`paddle_serving_p99_ms` reads an exact order statistic over the last
window instead of a bucket-boundary estimate.
"""
from __future__ import annotations

import collections
import time

from ..utils.metrics import Histogram, MetricsRegistry

__all__ = ["Histogram", "ServingMetrics", "GenerationMetrics",
           "RouterMetrics"]


class ServingMetrics:
    """All engine/server observability state, rendered as Prometheus text.

    Exposes (scraped by tools/serve_smoke.sh and read by bench.py):
      paddle_serving_qps                    completions/s over the window
      paddle_serving_p50_ms / _p99_ms       request latency order stats
      paddle_serving_batch_size             batch-size histogram
      paddle_serving_queue_latency_ms       submit→dispatch wait histogram
      paddle_serving_padding_waste_ratio    padded slots / total slots
      paddle_serving_requests_total{...}    accepted/rejected/… counters
      paddle_serving_compile_count          predictor bucket compiles
    """

    QPS_WINDOW_S = 60.0
    RESERVOIR = 4096

    def __init__(self):
        self.registry = MetricsRegistry()
        # the registry's RLock is THE lock (one lock for batcher thread,
        # N HTTP handler threads, and the /metrics scraper); computed
        # gauges run under it at scrape time, hence the *_locked helpers
        self._lock = self.registry._lock
        self.started_at = time.monotonic()
        reg = self.registry
        reg.gauge("paddle_serving_qps",
                  "completed requests per second over the trailing window",
                  fn=self._qps_locked)
        reg.gauge("paddle_serving_p50_ms",
                  "request latency p50 in milliseconds",
                  fn=lambda: self._quantile_locked(0.50))
        reg.gauge("paddle_serving_p99_ms",
                  "request latency p99 in milliseconds",
                  fn=lambda: self._quantile_locked(0.99))
        reg.gauge("paddle_serving_padding_waste_ratio",
                  "padded input elements / dispatched input elements "
                  "(batch-slot AND sequence padding)",
                  fn=self._waste_locked)
        reg.gauge("paddle_serving_compile_count",
                  "predictor shape-bucket compilations since start",
                  fn=lambda: self.compile_count)
        self._requests = reg.counter(
            "paddle_serving_requests_total",
            "request outcomes by result", label="result",
            preset=("accepted", "responses", "rejected_queue_full",
                    "rejected_draining", "deadline_expired", "cancelled",
                    "errors"),
            fixed=True)
        self.batch_size_hist = reg.histogram(
            "paddle_serving_batch_size",
            "requests coalesced per dispatched batch",
            [1, 2, 4, 8, 16, 32, 64, 128])
        self.queue_latency_hist = reg.histogram(
            "paddle_serving_queue_latency_ms",
            "milliseconds a request waited in the batch queue",
            [0.5, 1, 2, 5, 10, 20, 50, 100, 250, 500, 1000, 5000])
        self.request_latency_hist = reg.histogram(
            "paddle_serving_request_latency_ms",
            "end-to-end request latency in milliseconds",
            [1, 2, 5, 10, 20, 50, 100, 250, 500, 1000, 5000])
        self._latencies = collections.deque(maxlen=self.RESERVOIR)
        self._completions = collections.deque()  # monotonic stamps
        self.batch_slots_total = 0
        self.padded_slots_total = 0
        self.compile_count = 0

    @property
    def counters(self):
        """The request-outcome counts, dict-like (tests/engine read
        `metrics.counters["errors"]` as before the registry migration)."""
        return self._requests.values

    # -- recording hooks (engine/server threads) ---------------------------
    def count(self, name: str, n: int = 1):
        self._requests.inc(name, n)

    def observe_batch(self, n_requests: int, bucket_batch: int,
                      real_elems: int = None, total_elems: int = None):
        """Waste is counted in input ELEMENTS when provided (covers both
        batch-slot padding and sequence padding); falls back to
        slot-level accounting otherwise."""
        if total_elems is None:
            real_elems, total_elems = n_requests, bucket_batch
        with self._lock:
            self.batch_size_hist._observe_locked(n_requests)
            self.batch_slots_total += total_elems
            self.padded_slots_total += total_elems - real_elems

    def observe_queue_wait(self, seconds: float):
        self.queue_latency_hist.observe(seconds * 1e3)

    def observe_completion(self, latency_s: float):
        now = time.monotonic()
        with self._lock:
            self._requests.inc("responses")
            self.request_latency_hist._observe_locked(latency_s * 1e3)
            self._latencies.append(latency_s * 1e3)
            self._completions.append(now)
            cutoff = now - self.QPS_WINDOW_S
            while self._completions and self._completions[0] < cutoff:
                self._completions.popleft()

    def set_compile_count(self, n: int):
        with self._lock:
            self.compile_count = int(n)

    # -- derived values ----------------------------------------------------
    def _quantile_locked(self, q: float):
        if not self._latencies:
            return 0.0
        xs = sorted(self._latencies)
        idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
        return xs[idx]

    def _qps_locked(self, now=None):
        now = time.monotonic() if now is None else now
        if not self._completions:
            return 0.0
        span = max(1e-9, min(now - self.started_at, self.QPS_WINDOW_S))
        # ignore stamps older than the window (popped on observe, but the
        # deque can go stale when traffic stops)
        live = sum(1 for t in self._completions
                   if t >= now - self.QPS_WINDOW_S)
        return live / span

    def _waste_locked(self):
        return (self.padded_slots_total / self.batch_slots_total
                if self.batch_slots_total else 0.0)

    def snapshot(self) -> dict:
        """Programmatic view (bench.py serving fields, tests)."""
        with self._lock:
            return {
                "qps": round(self._qps_locked(), 2),
                "p50_ms": round(self._quantile_locked(0.50), 3),
                "p99_ms": round(self._quantile_locked(0.99), 3),
                "padding_waste_ratio": round(self._waste_locked(), 4),
                "batches": self.batch_size_hist.total,
                "mean_batch_size": round(
                    self.batch_size_hist.sum / self.batch_size_hist.total, 2)
                    if self.batch_size_hist.total else 0.0,
                "compile_count": self.compile_count,
                **{k: v for k, v in sorted(self.counters.items())},
            }

    def prometheus_text(self) -> str:
        return self.registry.prometheus_text()


class GenerationMetrics:
    """Decode-path observability for the continuous-batching generation
    engine (same private-registry pattern as ServingMetrics, so several
    engines coexist in one process).

    Exposes (scraped by tools/serve_smoke.sh, read by bench.py genserve):
      paddle_genserve_decode_tokens_per_sec  tokens streamed / s (window)
      paddle_genserve_ttft_p50_ms / _p99_ms  time-to-first-token
      paddle_genserve_inter_token_p50_ms / _p99_ms
                                             gap between a slot's tokens
      paddle_genserve_slot_occupancy         occupied / max_slots
      paddle_genserve_page_occupancy         KV pages in use / num_pages
      paddle_genserve_tokens_total           generated tokens
      paddle_genserve_requests_total{result} admitted/retired/preempted/…
      paddle_genserve_prefix_cache_hits_total / _misses_total
                                             prefix-cache admissions
      paddle_genserve_prefix_cache_hit_ratio hits / (hits + misses)
      paddle_genserve_spec_accept_ratio      accepted / proposed drafts
      paddle_genserve_prefill_chunks_total   chunked-prefill slices run
      paddle_genserve_compile_count          executables built at warmup
    """

    WINDOW_S = 60.0
    RESERVOIR = 4096

    def __init__(self, max_slots: int = 1, num_pages: int = 1):
        self.registry = MetricsRegistry()
        self._lock = self.registry._lock
        self.started_at = time.monotonic()
        self.max_slots = max(1, int(max_slots))
        self.num_pages = max(1, int(num_pages))
        reg = self.registry
        reg.gauge("paddle_genserve_decode_tokens_per_sec",
                  "generated tokens per second over the trailing window",
                  fn=self._tps_locked)
        reg.gauge("paddle_genserve_ttft_p50_ms",
                  "time-to-first-token p50 in milliseconds",
                  fn=lambda: self._quantile_locked(self._ttft, 0.50))
        reg.gauge("paddle_genserve_ttft_p99_ms",
                  "time-to-first-token p99 in milliseconds",
                  fn=lambda: self._quantile_locked(self._ttft, 0.99))
        reg.gauge("paddle_genserve_inter_token_p50_ms",
                  "inter-token latency p50 in milliseconds",
                  fn=lambda: self._quantile_locked(self._gaps, 0.50))
        reg.gauge("paddle_genserve_inter_token_p99_ms",
                  "inter-token latency p99 in milliseconds",
                  fn=lambda: self._quantile_locked(self._gaps, 0.99))
        reg.gauge("paddle_genserve_slot_occupancy",
                  "occupied decode slots / max_slots",
                  fn=lambda: self._occupied / self.max_slots)
        reg.gauge("paddle_genserve_page_occupancy",
                  "KV cache pages in use (reserved + prefix-shared) / "
                  "num_pages",
                  fn=lambda: self._pages_in_use / self.num_pages)
        reg.gauge("paddle_genserve_prefix_cache_hit_ratio",
                  "prefix-cache hits / (hits + misses) since start",
                  fn=self._prefix_ratio_locked)
        reg.gauge("paddle_genserve_spec_accept_ratio",
                  "accepted / proposed speculative draft tokens since "
                  "start (greedy lanes only; 0 when not speculating)",
                  fn=self._spec_ratio_locked)
        reg.gauge("paddle_genserve_compile_count",
                  "decode/prefill/insert executables compiled at warmup "
                  "(must not grow under traffic)",
                  fn=lambda: self.compile_count)
        self._requests = reg.counter(
            "paddle_genserve_requests_total",
            "generation request outcomes by result", label="result",
            preset=("admitted", "retired", "preempted",
                    "rejected_queue_full", "rejected_draining",
                    "rejected_pages_exhausted", "deadline_expired",
                    "cancelled", "errors"),
            fixed=True)
        self._tokens = reg.counter(
            "paddle_genserve_tokens_total", "generated tokens streamed")
        self._prefix_hits = reg.counter(
            "paddle_genserve_prefix_cache_hits_total",
            "admissions that reused cached prefix pages")
        self._prefix_misses = reg.counter(
            "paddle_genserve_prefix_cache_misses_total",
            "admissions that found no cached prefix")
        self._chunks = reg.counter(
            "paddle_genserve_prefill_chunks_total",
            "prefill chunks streamed into slot pages")
        self._spec_accepted = reg.counter(
            "paddle_genserve_spec_accepted_total",
            "draft proposals the target verification accepted")
        self._spec_proposed = reg.counter(
            "paddle_genserve_spec_proposed_total",
            "draft proposals offered to target verification")
        self._ttft = collections.deque(maxlen=self.RESERVOIR)
        self._gaps = collections.deque(maxlen=self.RESERVOIR)
        self._token_stamps = collections.deque()   # (monotonic, count)
        self._occupied = 0
        self._pages_in_use = 0
        self.compile_count = 0

    @property
    def counters(self):
        return self._requests.values

    # -- recording hooks (decode thread + HTTP threads) --------------------
    def count(self, name: str, n: int = 1):
        self._requests.inc(name, n)

    def observe_tokens(self, n: int):
        now = time.monotonic()
        self._tokens.inc(n)
        with self._lock:
            self._token_stamps.append((now, n))
            cutoff = now - self.WINDOW_S
            while self._token_stamps and self._token_stamps[0][0] < cutoff:
                self._token_stamps.popleft()

    def observe_ttft(self, seconds: float):
        with self._lock:
            self._ttft.append(seconds * 1e3)

    def observe_inter_token(self, seconds: float):
        with self._lock:
            self._gaps.append(seconds * 1e3)

    def set_occupancy(self, occupied: int):
        with self._lock:
            self._occupied = int(occupied)

    def set_page_occupancy(self, pages_in_use: int):
        with self._lock:
            self._pages_in_use = int(pages_in_use)

    def count_prefix(self, hit: bool):
        (self._prefix_hits if hit else self._prefix_misses).inc()

    def count_chunk(self, n: int = 1):
        self._chunks.inc(n)

    def observe_spec(self, accepted: int, proposed: int):
        self._spec_accepted.inc(accepted)
        self._spec_proposed.inc(proposed)

    def set_compile_count(self, n: int):
        with self._lock:
            self.compile_count = int(n)

    # -- derived values ----------------------------------------------------
    def _prefix_ratio_locked(self):
        hits = self._prefix_hits.value
        total = hits + self._prefix_misses.value
        return hits / total if total else 0.0

    def _spec_ratio_locked(self):
        proposed = self._spec_proposed.value
        return self._spec_accepted.value / proposed if proposed else 0.0

    def _quantile_locked(self, deque_, q: float):
        if not deque_:
            return 0.0
        xs = sorted(deque_)
        idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
        return xs[idx]

    def _tps_locked(self, now=None):
        now = time.monotonic() if now is None else now
        if not self._token_stamps:
            return 0.0
        span = max(1e-9, min(now - self.started_at, self.WINDOW_S))
        live = sum(n for t, n in self._token_stamps
                   if t >= now - self.WINDOW_S)
        return live / span

    def snapshot(self) -> dict:
        """Programmatic view (bench.py genserve fields, tests)."""
        with self._lock:
            return {
                "decode_tokens_per_sec": round(self._tps_locked(), 2),
                "ttft_p50_ms": round(
                    self._quantile_locked(self._ttft, 0.50), 3),
                "ttft_p99_ms": round(
                    self._quantile_locked(self._ttft, 0.99), 3),
                "inter_token_p50_ms": round(
                    self._quantile_locked(self._gaps, 0.50), 3),
                "inter_token_p99_ms": round(
                    self._quantile_locked(self._gaps, 0.99), 3),
                "slot_occupancy": round(self._occupied / self.max_slots, 3),
                "page_occupancy": round(
                    self._pages_in_use / self.num_pages, 3),
                "prefix_cache_hits": self._prefix_hits.value,
                "prefix_cache_misses": self._prefix_misses.value,
                "prefix_cache_hit_ratio": round(
                    self._prefix_ratio_locked(), 4),
                "spec_accept_ratio": round(self._spec_ratio_locked(), 4),
                "spec_proposed": self._spec_proposed.value,
                "prefill_chunks": self._chunks.value,
                "compile_count": self.compile_count,
                **{k: v for k, v in sorted(self.counters.items())},
            }

    def prometheus_text(self) -> str:
        return self.registry.prometheus_text()


class RouterMetrics:
    """Fleet-router observability (`serving/router.py`): per-replica
    routing decisions, backpressure, and replica health in one private
    registry, co-exposed through the router's /metrics (and embeddable
    in a `MonitorServer(extra_registries=...)` when the router rides an
    existing monitoring process).

    Routing reasons (the `reason` label on requests_total):
      prefix_hit       affinity table says this replica owns the
                       prompt's page-aligned prefix
      least_loaded     no affinity — picked the replica with the fewest
                       inflight requests
      health_failover  affinity replica was dead/draining, rerouted

    A 429 from a replica is BACKPRESSURE, not death: it bumps
    `paddle_router_backpressure_total{replica}` and the request retries
    elsewhere, but the replica's health-probe failure count is untouched
    (a loaded replica must not flap in and out of the fleet)."""

    def __init__(self):
        self.registry = MetricsRegistry()
        self._lock = self.registry._lock
        reg = self.registry
        self._requests = reg.counter(
            "paddle_router_requests_total",
            "requests routed, by target replica and routing reason",
            label=("replica", "reason"))
        self._backpressure = reg.counter(
            "paddle_router_backpressure_total",
            "429s absorbed per replica (request retried elsewhere; "
            "not a health-probe failure)", label="replica")
        self._failovers = reg.counter(
            "paddle_router_failovers_total",
            "requests re-dispatched to a survivor, by trigger "
            "(mid_stream = SSE resumed after a replica died under the "
            "stream; dispatch = the initial proxy attempt failed; "
            "hedge = a hedged duplicate was issued)",
            label="reason",
            preset=("mid_stream", "dispatch", "hedge"), fixed=True)
        self._budget_exhausted = reg.counter(
            "paddle_router_retry_budget_exhausted_total",
            "retries suppressed because the retry budget was empty "
            "(the request failed fast with 503 instead of storming a "
            "sick fleet)")
        self._deadline_rejected = reg.counter(
            "paddle_router_deadline_rejected_total",
            "requests rejected at admission because the estimated "
            "queue wait already exceeded their deadline")
        self._hedges = reg.counter(
            "paddle_router_hedges_total",
            "hedged non-streaming dispatches by outcome (won = the "
            "hedge finished first, lost = the primary did)",
            label="outcome", preset=("won", "lost"), fixed=True)
        self._healthy = 0
        self._inflight = 0
        self._epoch = 0
        self._ok = 0
        self._failed = 0
        self._recovery_ms = 0.0
        reg.gauge("paddle_router_replicas_healthy",
                  "replicas currently passing health probes",
                  fn=lambda: self._healthy)
        reg.gauge("paddle_router_inflight",
                  "requests currently being proxied",
                  fn=lambda: self._inflight)
        reg.gauge("paddle_router_membership_epoch",
                  "last fleet-coordinator membership epoch the router "
                  "applied (0 when running from a static replica list)",
                  fn=lambda: self._epoch)
        reg.gauge("paddle_fleet_availability_ratio",
                  "requests that returned a complete answer (failovers "
                  "included) over all finished requests; 1.0 = zero "
                  "client-visible failures",
                  fn=lambda: (self._ok / (self._ok + self._failed)
                              if (self._ok + self._failed) else 1.0))
        reg.gauge("paddle_router_failover_recovery_ms",
                  "last mid-stream failover's loss-to-resumed gap: "
                  "replica death detected under the stream to the "
                  "survivor's connection accepted, milliseconds",
                  fn=lambda: self._recovery_ms)

    def count_routed(self, replica: str, reason: str):
        self._requests.inc((str(replica), str(reason)))

    def count_backpressure(self, replica: str):
        self._backpressure.inc(str(replica))

    def count_failover(self, reason: str):
        self._failovers.inc(str(reason))

    def count_budget_exhausted(self):
        self._budget_exhausted.inc()

    def count_deadline_rejected(self):
        self._deadline_rejected.inc()

    def count_hedge(self, outcome: str):
        self._hedges.inc(str(outcome))

    def count_outcome(self, ok: bool):
        """One finished client request — the availability denominator.
        A failed-over request that eventually completed counts `ok`;
        only client-visible failures (5xx, dead stream) count failed."""
        with self._lock:
            if ok:
                self._ok += 1
            else:
                self._failed += 1

    def set_healthy(self, n: int):
        with self._lock:
            self._healthy = int(n)

    def set_epoch(self, n: int):
        with self._lock:
            self._epoch = int(n)

    def set_recovery_ms(self, ms: float):
        with self._lock:
            self._recovery_ms = round(float(ms), 3)

    def add_inflight(self, delta: int):
        with self._lock:
            self._inflight += int(delta)

    def snapshot(self) -> dict:
        with self._lock:
            denom = self._ok + self._failed
            return {
                "replicas_healthy": self._healthy,
                "inflight": self._inflight,
                "membership_epoch": self._epoch,
                "availability_ratio": (self._ok / denom) if denom else 1.0,
                "requests_ok": self._ok,
                "requests_failed": self._failed,
                "routed": {"|".join(k): v
                           for k, v in sorted(self._requests.values.items())},
                "backpressure": dict(sorted(
                    self._backpressure.values.items())),
                "failovers": dict(sorted(self._failovers.values.items())),
                "retry_budget_exhausted": self._budget_exhausted.value,
                "deadline_rejected": self._deadline_rejected.value,
                "hedges": dict(sorted(self._hedges.values.items())),
                "failover_recovery_ms": self._recovery_ms,
            }

    def prometheus_text(self) -> str:
        return self.registry.prometheus_text()
