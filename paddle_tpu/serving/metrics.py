"""Serving metrics: counters, histograms, and a Prometheus text endpoint.

Dependency-free (no prometheus_client): the exposition format is a few
lines of text (https://prometheus.io/docs/instrumenting/exposition_formats/)
and the serving engine needs exactly counters, histograms, and gauges.
Everything is guarded by one lock — the batcher thread, N HTTP handler
threads, and the /metrics scraper all touch the same state.

Quantiles (p50/p99) come from a bounded reservoir of recent request
latencies rather than histogram interpolation, so a smoke test scraping
`paddle_serving_p99_ms` reads an exact order statistic over the last
window instead of a bucket-boundary estimate.
"""
from __future__ import annotations

import bisect
import collections
import threading
import time

__all__ = ["Histogram", "ServingMetrics"]


class Histogram:
    """Cumulative-bucket histogram (Prometheus `histogram` type)."""

    def __init__(self, name: str, help_: str, buckets):
        self.name = name
        self.help = help_
        self.uppers = sorted(float(b) for b in buckets)
        self.counts = [0] * len(self.uppers)  # per-bucket (non-cumulative)
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float):
        self.total += 1
        self.sum += value
        i = bisect.bisect_left(self.uppers, value)
        if i < len(self.counts):
            self.counts[i] += 1

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        cum = 0
        for upper, c in zip(self.uppers, self.counts):
            cum += c
            le = f"{upper:g}"
            lines.append(f'{self.name}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {self.total}')
        lines.append(f"{self.name}_sum {self.sum:g}")
        lines.append(f"{self.name}_count {self.total}")
        return lines


class ServingMetrics:
    """All engine/server observability state, rendered as Prometheus text.

    Exposes (scraped by tools/serve_smoke.sh and read by bench.py):
      paddle_serving_qps                    completions/s over the window
      paddle_serving_p50_ms / _p99_ms       request latency order stats
      paddle_serving_batch_size             batch-size histogram
      paddle_serving_queue_latency_ms       submit→dispatch wait histogram
      paddle_serving_padding_waste_ratio    padded slots / total slots
      paddle_serving_requests_total{...}    accepted/rejected/… counters
      paddle_serving_compile_count          predictor bucket compiles
    """

    QPS_WINDOW_S = 60.0
    RESERVOIR = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        self.counters = collections.Counter()
        self.batch_size_hist = Histogram(
            "paddle_serving_batch_size",
            "requests coalesced per dispatched batch",
            [1, 2, 4, 8, 16, 32, 64, 128])
        self.queue_latency_hist = Histogram(
            "paddle_serving_queue_latency_ms",
            "milliseconds a request waited in the batch queue",
            [0.5, 1, 2, 5, 10, 20, 50, 100, 250, 500, 1000, 5000])
        self.request_latency_hist = Histogram(
            "paddle_serving_request_latency_ms",
            "end-to-end request latency in milliseconds",
            [1, 2, 5, 10, 20, 50, 100, 250, 500, 1000, 5000])
        self._latencies = collections.deque(maxlen=self.RESERVOIR)
        self._completions = collections.deque()  # monotonic stamps
        self.batch_slots_total = 0
        self.padded_slots_total = 0
        self.compile_count = 0

    # -- recording hooks (engine/server threads) ---------------------------
    def count(self, name: str, n: int = 1):
        with self._lock:
            self.counters[name] += n

    def observe_batch(self, n_requests: int, bucket_batch: int,
                      real_elems: int = None, total_elems: int = None):
        """Waste is counted in input ELEMENTS when provided (covers both
        batch-slot padding and sequence padding); falls back to
        slot-level accounting otherwise."""
        if total_elems is None:
            real_elems, total_elems = n_requests, bucket_batch
        with self._lock:
            self.batch_size_hist.observe(n_requests)
            self.batch_slots_total += total_elems
            self.padded_slots_total += total_elems - real_elems

    def observe_queue_wait(self, seconds: float):
        with self._lock:
            self.queue_latency_hist.observe(seconds * 1e3)

    def observe_completion(self, latency_s: float):
        now = time.monotonic()
        with self._lock:
            self.counters["responses"] += 1
            self.request_latency_hist.observe(latency_s * 1e3)
            self._latencies.append(latency_s * 1e3)
            self._completions.append(now)
            cutoff = now - self.QPS_WINDOW_S
            while self._completions and self._completions[0] < cutoff:
                self._completions.popleft()

    def set_compile_count(self, n: int):
        with self._lock:
            self.compile_count = int(n)

    # -- derived values ----------------------------------------------------
    def _quantile_locked(self, q: float):
        if not self._latencies:
            return 0.0
        xs = sorted(self._latencies)
        idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
        return xs[idx]

    def _qps_locked(self, now=None):
        now = time.monotonic() if now is None else now
        if not self._completions:
            return 0.0
        span = max(1e-9, min(now - self.started_at, self.QPS_WINDOW_S))
        # ignore stamps older than the window (popped on observe, but the
        # deque can go stale when traffic stops)
        live = sum(1 for t in self._completions
                   if t >= now - self.QPS_WINDOW_S)
        return live / span

    def snapshot(self) -> dict:
        """Programmatic view (bench.py serving fields, tests)."""
        with self._lock:
            waste = (self.padded_slots_total / self.batch_slots_total
                     if self.batch_slots_total else 0.0)
            return {
                "qps": round(self._qps_locked(), 2),
                "p50_ms": round(self._quantile_locked(0.50), 3),
                "p99_ms": round(self._quantile_locked(0.99), 3),
                "padding_waste_ratio": round(waste, 4),
                "batches": self.batch_size_hist.total,
                "mean_batch_size": round(
                    self.batch_size_hist.sum / self.batch_size_hist.total, 2)
                    if self.batch_size_hist.total else 0.0,
                "compile_count": self.compile_count,
                **{k: v for k, v in sorted(self.counters.items())},
            }

    def prometheus_text(self) -> str:
        with self._lock:
            lines = []
            lines.append("# HELP paddle_serving_qps completed requests per "
                         "second over the trailing window")
            lines.append("# TYPE paddle_serving_qps gauge")
            lines.append(f"paddle_serving_qps {self._qps_locked():g}")
            for q, name in ((0.50, "p50"), (0.99, "p99")):
                lines.append(f"# HELP paddle_serving_{name}_ms request "
                             f"latency {name} in milliseconds")
                lines.append(f"# TYPE paddle_serving_{name}_ms gauge")
                lines.append(f"paddle_serving_{name}_ms "
                             f"{self._quantile_locked(q):g}")
            waste = (self.padded_slots_total / self.batch_slots_total
                     if self.batch_slots_total else 0.0)
            lines.append("# HELP paddle_serving_padding_waste_ratio padded "
                         "input elements / dispatched input elements "
                         "(batch-slot AND sequence padding)")
            lines.append("# TYPE paddle_serving_padding_waste_ratio gauge")
            lines.append(f"paddle_serving_padding_waste_ratio {waste:g}")
            lines.append("# HELP paddle_serving_compile_count predictor "
                         "shape-bucket compilations since start")
            lines.append("# TYPE paddle_serving_compile_count gauge")
            lines.append(f"paddle_serving_compile_count {self.compile_count}")
            lines.append("# HELP paddle_serving_requests_total request "
                         "outcomes by result")
            lines.append("# TYPE paddle_serving_requests_total counter")
            for key in ("accepted", "responses", "rejected_queue_full",
                        "rejected_draining", "deadline_expired",
                        "cancelled", "errors"):
                lines.append(f'paddle_serving_requests_total'
                             f'{{result="{key}"}} {self.counters[key]}')
            lines.extend(self.batch_size_hist.render())
            lines.extend(self.queue_latency_hist.render())
            lines.extend(self.request_latency_hist.render())
            return "\n".join(lines) + "\n"
