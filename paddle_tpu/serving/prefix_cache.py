"""Host-side prefix cache: tokenized prompt prefix -> resident KV pages.

The shared-system-prompt-times-a-million-users pattern: identical
prompt prefixes should occupy the page pool ONCE.  This module is pure
host bookkeeping over the device-resident pool of serving/kv_cache.py —
it never touches a jax array and is owned by the engine's single decode
thread, so it needs no lock.

Sharing is full-page-only: a prompt of length L can share at most
``floor((L - 1) / page_size)`` pages (the -1 guarantees at least one
suffix token so admission always has a position to compute logits at,
and full-page alignment means the copy-on-write boundary page is always
the slot's own freshly allocated page — shared pages are strictly
read-only).  On a miss the admitting request registers one entry per
prefix page count (keys are the raw token bytes of each full-page
prefix), so a later request sharing ANY page-aligned prefix hits
regardless of how the two prompts' lengths differ.

Lifetime is refcount-per-page: a page is referenced by every cache
entry containing it plus every active slot pinned to it.  LRU eviction
(bounded entry count) and slot release decrement; pages reaching zero
are handed back to the engine, which returns them to the device free
stack through the ``reclaim`` executable.
"""
from __future__ import annotations

import collections

__all__ = ["PrefixCache"]


class PrefixCache:
    """Refcounted read-only shared KV pages keyed by prompt prefix."""

    def __init__(self, page_size: int, capacity: int = 1024):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.page_size = int(page_size)
        self.capacity = int(capacity)
        self._entries = collections.OrderedDict()  # key -> tuple(page ids)
        self._rc: dict[int, int] = {}              # page id -> refcount

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    @property
    def resident_pages(self) -> int:
        """Pages currently held resident by entries and/or slot pins —
        the scheduler subtracts these from the allocatable pool."""
        return len(self._rc)

    def shareable_pages(self, prompt_len: int) -> int:
        """Max pages of an L-token prompt that may ever be shared."""
        return max(0, (int(prompt_len) - 1) // self.page_size)

    # -- lookup / registration (engine decode thread only) -----------------
    def _key(self, prompt, n_pages: int) -> bytes:
        return prompt[:n_pages * self.page_size].tobytes()

    def lookup(self, prompt):
        """Longest cached page-aligned prefix of ``prompt`` (np.int32
        1-D).  Returns (n_shared_pages, page_ids tuple) — (0, ()) on a
        miss.  LRU-touches the hit entry; the caller pins the returned
        pages before any device work.  Idempotent and side-effect-free
        on a miss: the engine probes the backlog head every loop
        iteration while waiting for pages, so hit/miss METRICS are
        counted at actual admission (metrics.count_prefix), not here."""
        for j in range(self.shareable_pages(len(prompt)), 0, -1):
            pages = self._entries.get(self._key(prompt, j))
            if pages is not None:
                self._entries.move_to_end(self._key(prompt, j))
                return j, pages
        return 0, ()

    def register(self, prompt, row, j_hit: int, j_reg: int):
        """Register entries for every unshared full-page prefix of an
        admitted prompt: prefix page counts ``j_hit+1 .. j_reg`` map to
        ``row[:j]`` (the slot's just-fetched page-table row).  Returns
        pages freed by LRU eviction whose refcount reached zero — the
        caller reclaims them on device."""
        reclaim = []
        for j in range(j_hit + 1, j_reg + 1):
            key = self._key(prompt, j)
            if key in self._entries:
                continue
            pages = tuple(int(p) for p in row[:j])
            self._entries[key] = pages
            for p in pages:
                self._rc[p] = self._rc.get(p, 0) + 1
            while len(self._entries) > self.capacity:
                _, old = self._entries.popitem(last=False)
                reclaim.extend(self._unref(old))
        return reclaim

    def evict_idle(self, n_pages: int):
        """Pool-pressure eviction: pop LRU entries until at least
        ``n_pages`` pages have dropped to refcount zero, or the cache
        is empty.  Returns the freed page ids for device reclaim.

        Entries whose pages are still pinned by active slots free
        nothing when popped (the slot's unpin returns them later) —
        under pressure, future sharing is sacrificed before a queued
        request is starved.  The engine calls this from admission when
        ``can_admit`` fails on pages while cache residents hold the
        pool; without it a stream of DISTINCT prompts fills the pool
        with one-reader prefixes and the backlog head waits forever
        (entry-count capacity never trips on a small pool)."""
        reclaim = []
        while self._entries and len(reclaim) < n_pages:
            _, old = self._entries.popitem(last=False)
            reclaim.extend(self._unref(old))
        return reclaim

    # -- per-slot pinning --------------------------------------------------
    def pin(self, pages):
        """A slot started reading ``pages`` (its shared prefix + any
        pages it just registered): hold them resident until unpin."""
        for p in pages:
            p = int(p)
            self._rc[p] = self._rc.get(p, 0) + 1

    def unpin(self, pages):
        """The slot retired: drop its holds.  Returns pages whose
        refcount hit zero (their entries were evicted mid-flight) for
        device reclaim."""
        return self._unref(int(p) for p in pages)

    def _unref(self, pages):
        freed = []
        for p in pages:
            p = int(p)
            n = self._rc.get(p, 0) - 1
            if n <= 0:
                self._rc.pop(p, None)
                freed.append(p)
            else:
                self._rc[p] = n
        return freed
