"""Prefix-cache-aware, fault-tolerant fleet router over N replicas.

One stdlib HTTP endpoint in front of N `ServingServer` replicas, each
running its own `GenerationEngine` (own page pool, own prefix cache).
The reference scaled serving by handing every process its own
AnalysisPredictor behind an external L4 balancer (SURVEY §4c) — blind
round-robin, so two requests sharing a 4k-token system prompt land on
different predictors and BOTH pay the full prefill.  This router makes
the placement decision cache-topology-aware:

  prefix_hit       the prompt's page-aligned prefix (the exact region
                   `prefix_cache.shareable_pages` would share) hashes to
                   an affinity entry — route to the replica whose prefix
                   cache already owns those KV pages, so the replica-side
                   lookup hits and prefill skips the shared pages
  least_loaded     no affinity yet — route to the replica with the
                   fewest router-side inflight requests, then remember
                   the prefix → replica binding for the next caller
  health_failover  the affinity replica is dead (>= `dead_after`
                   consecutive /healthz probe failures) — re-route to
                   the least-loaded live replica and REBIND the prefix
                   (its pages are gone with the replica; stickiness to a
                   corpse would re-miss forever)

Fault tolerance (the brpc-transport parity layer — the reference's PS
fleet baked retries/health-checks/failover into the RPC substrate,
SURVEY §2.5):

  * elastic membership — given ``coord=host:port`` (the serving
    supervisor's PodCoordinator), the router subscribes to membership
    epochs: a dead rank is evicted on the EPOCH DELTA, faster than
    `dead_after` failed probes, and a supervisor-respawned rank rejoins
    (fresh URL from the coordinator KV) without a router restart.
  * mid-stream failover — every streaming /generate is journaled
    (original payload + tokens relayed so far).  When the upstream dies
    mid-stream the request is re-admitted on a survivor with the emitted
    prefix appended to the prompt and ``resume_pos`` set, so the SSE
    stream continues at the next token: greedy output is bitwise the
    uninterrupted run, sampled output resumes on the same PRNG chain.
  * retry budget — retries (dispatch failovers, mid-stream resumes)
    spend from a token bucket refilled by successful traffic
    (`FLAGS_router_retry_budget_ratio` per success, floor
    `FLAGS_router_retry_budget_min`); an empty budget degrades to a
    fast 503 instead of a retry storm against a sick fleet.
  * circuit breaker — `FLAGS_router_breaker_threshold` consecutive
    REQUEST failures stop dispatch to a replica before the probe loop
    catches up; after `FLAGS_router_breaker_cooldown_s` one trial
    request may re-probe it.
  * deadline-aware admission — a request whose `deadline_ms` is already
    smaller than the estimated queue wait on the chosen replica is
    rejected 504 at the router (no doomed dispatch).
  * hedged dispatch — non-streaming requests are duplicated to a second
    replica once the first has been outstanding max(observed p99,
    `FLAGS_router_hedge_floor_ms`); first answer wins.  Off by default.

Backpressure is not death: a replica answering 429 (generation queue
full) is healthy-but-loaded.  The router counts it
(`paddle_router_backpressure_total{replica}`), retries the request on
the remaining live replicas WITHOUT spending retry budget, and does not
touch the health-probe failure count — a replica must never flap out of
the fleet just for being busy (the flap would dump its whole
prefix-cache working set).  Probe flap damping works the other way too:
a replica marked dead needs `FLAGS_router_healthy_after` CONSECUTIVE
probe successes before it takes traffic again, and probe start times
are staggered across replicas so a fleet restart is not a thundering
herd of simultaneous probes.

Tracing: the incoming W3C `traceparent` (or a fresh head-sampled root)
becomes a `router.generate` child span whose context is forwarded to
the replica, so `/debug/spans?trace_id=` shows client → router →
replica server.generate → gen.prefill/gen.decode as ONE trace across
the hop.

`/metrics` federation: the router serves its own `RouterMetrics`
registry (co-exposable in-process via
`MonitorServer(extra_registries=[router.metrics.registry])`) followed by
every live replica's scrape under a `# replica=<name> <url>` banner —
one curl shows fleet routing counters AND per-replica genserve gauges.

Shutdown mirrors the server's latch-drain contract: SIGTERM stops new
admissions (healthz flips to draining), inflight proxied requests
finish, then the listener closes and "router drain clean" is logged
(tools/serve_smoke.sh greps it, then SIGTERMs the replicas).
"""
from __future__ import annotations

import collections
import hashlib
import http.client
import json
import logging
import queue as _queue
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..distributed.resilience import PreemptionGuard
from ..framework import flags as _flags
from ..monitor import tracing as _tracing
from .metrics import RouterMetrics

logger = logging.getLogger("paddle_tpu.serving.router")

__all__ = ["FleetRouter", "Replica", "RetryBudget"]


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 128


class RetryBudget:
    """Token bucket capping retries at a fraction of successful traffic.

    Each successful request deposits `ratio` tokens (so a healthy fleet
    earns the right to absorb failures); each retry withdraws one whole
    token.  The bucket starts at — and is floored against growing past
    `cap` — so a cold router can still fail over, but a fleet that is
    ONLY failing drains the bucket and every further request fails fast
    with 503 instead of multiplying load: the retry-storm breaker the
    reference got from brpc's `max_retry` + backup-request budget."""

    def __init__(self, ratio: float, min_budget: float, cap: float = 100.0):
        self.ratio = float(ratio)
        self.min = float(min_budget)
        self.cap = max(float(cap), self.min)
        self.balance = self.min
        self._lock = threading.Lock()

    def deposit(self):
        with self._lock:
            self.balance = min(self.balance + self.ratio, self.cap)

    def withdraw(self) -> bool:
        """Take one retry token; False = budget exhausted, do not retry."""
        with self._lock:
            if self.balance >= 1.0:
                self.balance -= 1.0
                return True
            return False


class Replica:
    """Router-side view of one generation server: health-probe state,
    circuit-breaker state + inflight accounting.  All mutation happens
    under the router lock."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.inflight = 0
        self.fails = 0          # consecutive /healthz probe failures
        self.succs = 0          # consecutive probe successes while dead
        self.alive = True       # optimistic until probes say otherwise
        self.draining = False
        self.brk_fails = 0      # consecutive REQUEST failures (breaker)
        self.brk_until = 0.0    # breaker holds dispatch until this time

    def reset_fresh(self, url: str = None):
        """A brand-new process answers at this slot (supervisor respawn
        observed via the membership channel): forget the corpse's
        probe/breaker history."""
        if url is not None:
            self.url = url.rstrip("/")
        self.fails = self.succs = self.brk_fails = 0
        self.brk_until = 0.0
        self.alive = True
        self.draining = False

    def snapshot(self) -> dict:
        return {"name": self.name, "url": self.url,
                "alive": self.alive, "draining": self.draining,
                "inflight": self.inflight, "probe_fails": self.fails,
                "breaker_fails": self.brk_fails}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _send(self, code: int, body: bytes, ctype="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if code in (429, 503):
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj):
        self._send(code, json.dumps(obj).encode())

    def do_GET(self):  # noqa: N802 - http.server API
        router = self.server.owner
        if self.path == "/healthz":
            body = {"status": "draining" if router.draining else "ok",
                    "replicas": [r.snapshot() for r in router.replicas],
                    "uptime_s": router.uptime_s}
            self._send_json(503 if router.draining else 200, body)
        elif self.path == "/metrics":
            self._send(200, router.federated_metrics().encode(),
                       ctype="text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802 - http.server API
        router = self.server.owner
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n)
        if self.path not in ("/generate", "/predict"):
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        if router.draining:
            self._send_json(503, {"error": "router draining"})
            return
        tracer = _tracing.default_tracer()
        span = tracer.start_span("router.generate",
                                 traceparent=self.headers.get("traceparent"))
        try:
            if self.path == "/predict":
                router._route_predict(self, raw, span)
            else:
                router._route_generate(self, raw, span)
        finally:
            span.end()

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("%s - %s", self.address_string(), fmt % args)


class FleetRouter:
    """N generation replicas behind one endpoint with prefix-affinity,
    least-loaded fallback, health/epoch failover, journaled mid-stream
    resume, retry budgets, circuit breakers and SSE pass-through."""

    def __init__(self, replica_urls, host="127.0.0.1", port=0, *,
                 page_size=None, probe_interval_s=None, dead_after=None,
                 request_timeout_s=120.0, install_signal_handlers=True,
                 drain_timeout_s=30.0, coord=None, healthy_after=None,
                 retry_budget_ratio=None, retry_budget_min=None,
                 breaker_threshold=None, breaker_cooldown_s=None,
                 hedge_floor_ms=None, replica_slots=None,
                 membership_poll_s=None):
        if not replica_urls and not coord:
            raise ValueError("FleetRouter needs at least one replica url "
                             "(or a fleet coordinator address)")
        self.replicas = [Replica(f"r{i}", u)
                         for i, u in enumerate(replica_urls or ())]
        self.page_size = int(
            page_size or _flags.flag("FLAGS_genserve_page_size", 16))
        self.probe_interval_s = float(
            probe_interval_s
            or _flags.flag("FLAGS_router_probe_interval_s", 0.5))
        self.dead_after = int(
            dead_after or _flags.flag("FLAGS_router_dead_after", 3))
        self.healthy_after = int(
            healthy_after or _flags.flag("FLAGS_router_healthy_after", 2))
        self.breaker_threshold = int(
            breaker_threshold
            or _flags.flag("FLAGS_router_breaker_threshold", 3))
        self.breaker_cooldown_s = float(
            breaker_cooldown_s
            or _flags.flag("FLAGS_router_breaker_cooldown_s", 2.0))
        self.hedge_floor_ms = float(
            hedge_floor_ms
            if hedge_floor_ms is not None
            else _flags.flag("FLAGS_router_hedge_floor_ms", 0.0))
        self.replica_slots = int(
            replica_slots or _flags.flag("FLAGS_router_replica_slots", 4))
        self.membership_poll_s = float(
            membership_poll_s
            or _flags.flag("FLAGS_fleet_membership_poll_s", 0.1))
        self.budget = RetryBudget(
            retry_budget_ratio if retry_budget_ratio is not None
            else _flags.flag("FLAGS_router_retry_budget_ratio", 0.1),
            retry_budget_min if retry_budget_min is not None
            else _flags.flag("FLAGS_router_retry_budget_min", 5.0))
        self.request_timeout_s = float(request_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._install_signals = install_signal_handlers
        self._host = host
        self._requested_port = int(port)
        self.metrics = RouterMetrics()
        self._lock = threading.RLock()
        self._affinity: dict[str, int] = {}   # prefix hash -> replica idx
        self._coord = coord
        self._pod = None
        self._member_epoch = 0
        self._coord_dead: set[int] = set()
        self._latencies = collections.deque(maxlen=256)
        self._lat_ewma_s = 0.0
        self._httpd = None
        self._guard = None
        self._threads = []
        self._done = threading.Event()
        self._stop_probe = threading.Event()
        self._drain_clean = None
        self._shutdown_once = threading.Lock()
        self._started_at = None
        self.draining = False

    # -- lifecycle ---------------------------------------------------------
    @property
    def uptime_s(self) -> float:
        return round(time.monotonic() - self._started_at, 1) \
            if self._started_at is not None else 0.0

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd \
            else self._requested_port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self) -> "FleetRouter":
        if self._coord:
            from ..distributed.podcoord import PodClient

            # rank -1: the router is a membership OBSERVER, never a
            # heartbeating member — it must not count toward liveness
            self._pod = PodClient(self._coord, rank=-1)
            self._bootstrap_membership()
        self._probe_all()  # synchronous first pass: route correctly from
        self._httpd = _HTTPServer((self._host, self._requested_port),
                                  _Handler)  # request #1, not probe #2
        self._httpd.owner = self
        self._started_at = time.monotonic()
        if self._install_signals:
            self._guard = PreemptionGuard()
            self._guard.__enter__()
        t_serve = threading.Thread(target=self._httpd.serve_forever,
                                   kwargs={"poll_interval": 0.05},
                                   daemon=True, name="paddle-router-http")
        t_probe = threading.Thread(target=self._probe_loop, daemon=True,
                                   name="paddle-router-probe")
        t_watch = threading.Thread(target=self._watch, daemon=True,
                                   name="paddle-router-sigwatch")
        self._threads = [t_serve, t_probe, t_watch]
        if self._pod is not None:
            t_member = threading.Thread(target=self._membership_loop,
                                        daemon=True,
                                        name="paddle-router-membership")
            self._threads.append(t_member)
        for t in self._threads:
            t.start()
        logger.info("router on %s over %d replicas (%s)%s", self.url,
                    len(self.replicas),
                    ", ".join(r.url for r in self.replicas),
                    f" coord={self._coord}" if self._coord else "")
        return self

    def _watch(self):
        while not self._done.wait(0.05):
            if self._guard is not None and self._guard.preempted:
                logger.warning("signal %s latched — draining router",
                               self._guard.signum)
                self.shutdown()
                return

    def shutdown(self) -> bool:
        """Drain: reject new admissions, let inflight proxied requests
        finish, close the listener.  Idempotent; True = clean."""
        with self._shutdown_once:
            if self._drain_clean is not None:
                return self._drain_clean
            self.draining = True
            self._stop_probe.set()
            deadline = time.monotonic() + self.drain_timeout_s
            while time.monotonic() < deadline:
                with self._lock:
                    if all(r.inflight == 0 for r in self.replicas):
                        break
                time.sleep(0.02)
            with self._lock:
                clean = all(r.inflight == 0 for r in self.replicas)
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()
            if self._guard is not None:
                self._guard.__exit__(None, None, None)
                self._guard = None
            self._drain_clean = clean
            self._done.set()
            logger.info("router drain %s", "clean" if clean else "TIMED OUT")
            return clean

    def wait(self, timeout=None) -> int:
        if not self._done.wait(timeout):
            return -1
        for t in self._threads:
            t.join(5.0)
        return 0 if self._drain_clean else 1

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- elastic membership (PR-16 pod coordinator) ------------------------
    def _bootstrap_membership(self, timeout_s: float = 30.0):
        """Initial replica discovery: block until at least one live rank
        has registered its URL in the coordinator KV (replicas register
        right after their readiness line, so this bounds router start to
        fleet bring-up, not probe timeouts)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                self._membership_sync(kv_timeout_s=2.0)
            except (OSError, RuntimeError) as e:
                logger.debug("membership bootstrap retry: %s", e)
            if self.replicas:
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"no replica registered with coordinator {self._coord} "
            f"within {timeout_s:g}s")

    def _membership_loop(self):
        while not self._stop_probe.wait(self.membership_poll_s):
            try:
                m = self._pod.membership()
            except (OSError, RuntimeError):
                continue  # coordinator briefly unreachable; probes rule
            if int(m["epoch"]) == self._member_epoch:
                continue
            try:
                self._membership_sync(membership=m)
            except (OSError, RuntimeError) as e:
                logger.warning("membership sync failed: %s", e)

    def _membership_sync(self, membership=None, kv_timeout_s: float = 2.0):
        """Apply one membership snapshot: evict coordinator-declared-dead
        ranks on the EPOCH DELTA (no probe-timeout wait) and (re)admit
        live ranks at their registered URL — a supervisor respawn shows
        up here as a fresh URL under the same rank."""
        m = membership if membership is not None else self._pod.membership()
        epoch = int(m["epoch"])
        live = [int(r) for r in m.get("live", ())]
        dead = {int(r): why for r, why in m.get("dead", {}).items()}
        urls = {}
        for r in live:
            raw = self._pod.kv_get(f"serving/replica/{r}/url",
                                   timeout_s=kv_timeout_s)
            if raw:
                urls[r] = raw.decode("utf-8")
        with self._lock:
            by_name = {rep.name: rep for rep in self.replicas}
            for r, why in dead.items():
                rep = by_name.get(f"r{r}")
                if rep is not None and rep.alive:
                    rep.alive = False
                    rep.fails = max(rep.fails, self.dead_after)
                    rep.succs = 0
                    logger.warning(
                        "epoch %d: replica %s evicted (%s) ahead of "
                        "probe timeout", epoch, rep.name, why)
            for r, u in urls.items():
                rep = by_name.get(f"r{r}")
                if rep is None:
                    rep = Replica(f"r{r}", u)
                    self.replicas.append(rep)
                    logger.info("epoch %d: replica %s joined at %s",
                                epoch, rep.name, u)
                elif rep.url != u.rstrip("/") or r in self._coord_dead:
                    # same rank, new process (respawn) — trust the
                    # supervisor's re-admission; probes keep watching
                    logger.info("epoch %d: replica %s respawned at %s",
                                epoch, rep.name, u)
                    rep.reset_fresh(u)
            self._coord_dead = set(dead)
            self._member_epoch = epoch
            self.metrics.set_epoch(epoch)
            self.metrics.set_healthy(
                sum(1 for rp in self.replicas if rp.alive))

    # -- health probing ----------------------------------------------------
    def _probe_one(self, rep: Replica):
        try:
            req = urllib.request.Request(rep.url + "/healthz")
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                ok = resp.status == 200
                rep.draining = False
        except urllib.error.HTTPError as e:
            # 503 healthz = replica draining: stop routing to it, but it
            # is answering — not a crash
            ok = False
            rep.draining = (e.code == 503)
        except OSError:
            ok = False
            rep.draining = False
        with self._lock:
            if ok:
                rep.fails = 0
                if rep.alive:
                    rep.succs = 0
                else:
                    # flap damping: a dead replica must string together
                    # `healthy_after` consecutive probe successes before
                    # taking traffic again — one lucky probe of a sick
                    # replica must not re-admit it
                    rep.succs += 1
                    if rep.succs >= self.healthy_after:
                        rep.succs = 0
                        rep.brk_fails = 0
                        rep.brk_until = 0.0
                        rep.alive = True
                        logger.info("replica %s healthy again after %d "
                                    "consecutive probe successes",
                                    rep.name, self.healthy_after)
            else:
                rep.fails += 1
                rep.succs = 0
                if rep.fails >= self.dead_after or rep.draining:
                    rep.alive = False

    def _update_healthy(self):
        with self._lock:
            self.metrics.set_healthy(
                sum(1 for r in self.replicas if r.alive))

    def _probe_all(self):
        for rep in list(self.replicas):
            self._probe_one(rep)
        self._update_healthy()

    def _probe_loop(self):
        """Staggered probing: one replica every interval/N seconds
        instead of the whole fleet back-to-back — a restarting fleet is
        not greeted by a thundering herd of simultaneous probes."""
        while not self._stop_probe.is_set():
            reps = list(self.replicas)
            step = self.probe_interval_s / max(1, len(reps))
            for rep in reps:
                if self._stop_probe.wait(step):
                    return
                self._probe_one(rep)
                self._update_healthy()

    # -- routing policy ----------------------------------------------------
    def _prefix_key(self, prompt) -> str | None:
        """Hash of the page-aligned shareable prefix — EXACTLY the
        region the replica's PrefixCache would share
        (`shareable_pages`: the last page is never shared because the
        next generated token writes into it)."""
        n_pages = max(0, (len(prompt) - 1) // self.page_size)
        if n_pages == 0:
            return None
        head = prompt[:n_pages * self.page_size]
        return hashlib.sha1(
            b",".join(b"%d" % int(t) for t in head)).hexdigest()

    def _breaker_open(self, rep: Replica, now: float) -> bool:
        return rep.brk_fails >= self.breaker_threshold \
            and now < rep.brk_until

    def _note_request_failure(self, rep: Replica):
        with self._lock:
            rep.brk_fails += 1
            if rep.brk_fails >= self.breaker_threshold:
                rep.brk_until = time.monotonic() + self.breaker_cooldown_s

    def _note_request_success(self, rep: Replica):
        with self._lock:
            rep.brk_fails = 0
            rep.brk_until = 0.0

    def _evict(self, rep: Replica, why: str):
        """Immediate eviction on hard request-path evidence (a severed
        in-flight stream beats any probe): the replica re-earns traffic
        via `healthy_after` probe successes or a membership re-admit."""
        with self._lock:
            if rep.alive:
                rep.alive = False
                rep.fails = max(rep.fails, self.dead_after)
                rep.succs = 0
                logger.warning("replica %s evicted: %s", rep.name, why)
        self._update_healthy()

    def _pick(self, key, exclude=()):
        """(replica, reason) under the routing policy; None when no live
        replica remains.  `exclude`: replicas already tried this request
        (429 backpressure / failure retries).  Breaker-open replicas are
        skipped exactly like dead ones."""
        now = time.monotonic()
        with self._lock:
            live = [r for r in self.replicas
                    if r.alive and r.name not in exclude
                    and not self._breaker_open(r, now)]
            if not live:
                return None, None
            if key is not None:
                idx = self._affinity.get(key)
                if idx is not None and idx < len(self.replicas):
                    aff = self.replicas[idx]
                    if aff in live:
                        return aff, "prefix_hit"
                    # affinity points at a dead/busy replica: rebind
                    reason = "health_failover" if not aff.alive \
                        else "least_loaded"
                else:
                    reason = "least_loaded"
            else:
                reason = "least_loaded"
            rep = min(live, key=lambda r: (r.inflight, r.name))
            if key is not None:
                self._affinity[key] = self.replicas.index(rep)
            return rep, reason

    # -- latency model (deadline admission + hedging) ----------------------
    def _observe_latency(self, seconds: float):
        with self._lock:
            self._latencies.append(seconds)
            a = 0.1
            self._lat_ewma_s = seconds if self._lat_ewma_s == 0.0 \
                else (1 - a) * self._lat_ewma_s + a * seconds

    def _p99_s(self) -> float:
        with self._lock:
            if not self._latencies:
                return 0.0
            xs = sorted(self._latencies)
            return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    def _est_wait_ms(self, rep: Replica) -> float:
        """Estimated queue wait on `rep` before THIS request starts
        decoding: requests beyond the replica's slot count wait roughly
        one mean service time per occupied wave of slots."""
        with self._lock:
            waiting = max(0, rep.inflight + 1 - self.replica_slots)
            return (waiting * self._lat_ewma_s * 1e3
                    / max(1, self.replica_slots))

    def _hedge_delay_s(self) -> float:
        if self.hedge_floor_ms <= 0:
            return 0.0
        return max(self.hedge_floor_ms / 1e3, self._p99_s())

    def _deadline_hopeless(self, handler, rep, payload, span) -> bool:
        """Deadline-aware admission: reject NOW when the estimated queue
        wait alone already exceeds the request's deadline — a doomed
        dispatch would only add load to a replica that is behind."""
        deadline_ms = payload.get("deadline_ms")
        if deadline_ms is None:
            return False
        est = self._est_wait_ms(rep)
        if est <= float(deadline_ms):
            return False
        self.metrics.count_deadline_rejected()
        span.set_attr("status", "deadline_rejected")
        handler._send_json(
            504, {"error": "deadline unmeetable: estimated queue wait "
                           f"{est:.0f}ms exceeds deadline_ms "
                           f"{deadline_ms}"})
        return True

    # -- proxying ----------------------------------------------------------
    def _route_generate(self, handler, raw, span):
        try:
            payload = json.loads(raw or b"{}")
            prompt = payload.get("prompt") or []
            stream = bool(payload.get("stream", False))
        except ValueError:
            handler._send_json(400, {"error": "bad request: invalid JSON"})
            return
        key = self._prefix_key(prompt)
        if stream:
            self._route_stream(handler, payload, raw, span, key)
        else:
            self._route_unary(handler, payload, raw, span, "/generate", key)

    def _route_predict(self, handler, raw, span):
        try:
            payload = json.loads(raw or b"{}")
        except ValueError:
            handler._send_json(400, {"error": "bad request: invalid JSON"})
            return
        self._route_unary(handler, payload, raw, span, "/predict", None)

    def _route_unary(self, handler, payload, raw, span, path, key):
        """Non-streaming dispatch loop: backpressure retries are free,
        failure retries (transport / replica 5xx) spend retry budget,
        hedging duplicates slow dispatches when enabled."""
        tried: set[str] = set()
        saw_failure = False
        while True:
            rep, reason = self._pick(key, exclude=tried)
            if rep is None:
                if saw_failure:
                    span.set_attr("status", "no_live_replica")
                    handler._send_json(
                        503, {"error": "request failed on every live "
                                       "replica"})
                    self.metrics.count_outcome(ok=False)
                elif tried:   # every live replica answered 429
                    span.set_attr("status", "backpressure_exhausted")
                    handler._send_json(
                        429, {"error": "all replicas at capacity"})
                else:
                    span.set_attr("status", "no_live_replica")
                    handler._send_json(
                        503, {"error": "no live replica"})
                    self.metrics.count_outcome(ok=False)
                return
            if self._deadline_hopeless(handler, rep, payload, span):
                return
            tried.add(rep.name)
            kind, status, body, ctype = self._dispatch_unary(
                rep, reason, raw, span, path, tried)
            if kind == "backpressure":
                self.metrics.count_backpressure(rep.name)
                continue
            if kind == "failed":
                saw_failure = True
                if self.budget.withdraw():
                    self.metrics.count_failover("dispatch")
                    continue
                self.metrics.count_budget_exhausted()
                span.set_attr("status", "retry_budget_exhausted")
                handler._send_json(
                    503, {"error": "retry budget exhausted; last "
                                   f"upstream status {status}"})
                self.metrics.count_outcome(ok=False)
                return
            handler._send(status, body, ctype)
            if 200 <= status < 300:
                self.metrics.count_outcome(ok=True)
            elif status >= 500:
                self.metrics.count_outcome(ok=False)
            return

    def _dispatch_unary(self, rep, reason, raw, span, path, tried):
        """One (possibly hedged) upstream POST.  Returns (kind, status,
        body, ctype) with kind in ok|backpressure|failed|definitive."""
        delay = self._hedge_delay_s()
        if delay <= 0:
            return self._upstream(rep, reason, raw, span, path)
        results: _queue.Queue = _queue.Queue()

        def run(r, rsn, tag):
            results.put((tag, self._upstream(r, rsn, raw, span, path)))

        threading.Thread(target=run, args=(rep, reason, "primary"),
                         daemon=True).start()
        try:
            tag, out = results.get(timeout=delay)
        except _queue.Empty:
            hedge_rep, _ = self._pick(None, exclude=tried)
            if hedge_rep is None:
                tag, out = results.get()   # nobody to hedge to; wait
            else:
                self.metrics.count_failover("hedge")
                threading.Thread(
                    target=run, args=(hedge_rep, "hedge", "hedge"),
                    daemon=True).start()
                tag, out = results.get()   # first answer wins
                if out[0] == "failed":
                    # the first finisher failed — the race has a second
                    # runner, prefer its (possibly good) answer
                    tag, out = results.get()
                self.metrics.count_hedge(
                    "won" if tag == "hedge" else "lost")
        return out

    def _upstream(self, rep, reason, raw, span, path):
        """One upstream POST to `rep`, fully buffered (non-streaming).
        Pure: never touches the client handler, so hedge threads can
        race it safely."""
        span.set_attr("replica", rep.name)
        span.set_attr("reason", reason)
        headers = {"Content-Type": "application/json",
                   "traceparent": span.traceparent}
        req = urllib.request.Request(rep.url + path, data=raw,
                                     headers=headers, method="POST")
        with self._lock:
            rep.inflight += 1
        self.metrics.add_inflight(1)
        t0 = time.monotonic()
        try:
            try:
                resp = urllib.request.urlopen(
                    req, timeout=self.request_timeout_s)
            except urllib.error.HTTPError as e:
                body = e.read()
                ctype = e.headers.get("Content-Type", "application/json")
                if e.code == 429:
                    return "backpressure", 429, body, ctype
                if e.code >= 500:
                    self._note_request_failure(rep)
                    return "failed", e.code, body, ctype
                return "definitive", e.code, body, ctype
            except OSError as e:
                self._note_request_failure(rep)
                body = json.dumps(
                    {"error": f"replica {rep.name} unreachable: {e}"}
                ).encode()
                return "failed", 502, body, "application/json"
            with resp:
                body = resp.read()
                ctype = resp.headers.get("Content-Type",
                                         "application/json")
            self.metrics.count_routed(rep.name, reason)
            self._note_request_success(rep)
            self.budget.deposit()
            self._observe_latency(time.monotonic() - t0)
            return "definitive", resp.status, body, ctype
        finally:
            with self._lock:
                rep.inflight -= 1
            self.metrics.add_inflight(-1)

    # -- streaming with journaled mid-stream failover ----------------------
    def _route_stream(self, handler, payload, raw, span, key):
        """SSE proxy with a request journal: every relayed token is
        recorded; if the upstream dies mid-stream the request is
        re-admitted on a survivor with the emitted prefix appended to
        the prompt and the PRNG chain fast-forwarded (`resume_pos`), so
        the client stream continues at the next token with no failed
        request — greedy output bitwise the uninterrupted run."""
        prompt = list(payload.get("prompt") or [])
        max_new = int(payload.get("max_new_tokens", 32))
        base_resume = int(payload.get("resume_pos", 0))
        emitted: list[int] = []
        state = {"headers_sent": False}
        tried: set[str] = set()
        saw_failure = False
        saw_backpressure = False
        t0 = time.monotonic()
        t_loss = None

        def fail_out(msg, status=503):
            if state["headers_sent"]:
                self._write_event(handler, {
                    "done": True, "tokens": len(emitted), "error": msg})
                self._end_chunks(handler)
            else:
                handler._send_json(status, {"error": msg})
            self.metrics.count_outcome(ok=False)

        while True:
            rep, reason = self._pick(key, exclude=tried)
            if rep is None:
                if saw_backpressure and not saw_failure \
                        and not state["headers_sent"]:
                    span.set_attr("status", "backpressure_exhausted")
                    handler._send_json(
                        429, {"error": "all replicas at capacity"})
                else:
                    span.set_attr("status", "no_live_replica")
                    fail_out("no live replica")
                return
            if not emitted \
                    and self._deadline_hopeless(handler, rep, payload,
                                                span):
                return
            tried.add(rep.name)
            if emitted:
                body = json.dumps({
                    **payload,
                    "prompt": prompt + emitted,
                    "max_new_tokens": max_new - len(emitted),
                    "resume_pos": base_resume + len(emitted),
                }).encode()
            else:
                body = raw
            span.set_attr("replica", rep.name)
            span.set_attr("reason", reason)
            headers = {"Content-Type": "application/json",
                       "traceparent": span.traceparent}
            req = urllib.request.Request(rep.url + "/generate", data=body,
                                         headers=headers, method="POST")
            with self._lock:
                rep.inflight += 1
            self.metrics.add_inflight(1)
            try:
                try:
                    resp = urllib.request.urlopen(
                        req, timeout=self.request_timeout_s)
                except urllib.error.HTTPError as e:
                    err_body = e.read()
                    if e.code == 429:
                        saw_backpressure = True
                        self.metrics.count_backpressure(rep.name)
                        continue
                    if e.code < 500 and not state["headers_sent"]:
                        # the replica judged the request malformed — a
                        # definitive answer, not a fleet failure
                        handler._send(e.code, err_body,
                                      e.headers.get("Content-Type",
                                                    "application/json"))
                        return
                    saw_failure = True
                    self._note_request_failure(rep)
                    if not self.budget.withdraw():
                        self.metrics.count_budget_exhausted()
                        span.set_attr("status", "retry_budget_exhausted")
                        fail_out("retry budget exhausted")
                        return
                    self.metrics.count_failover(
                        "mid_stream" if emitted else "dispatch")
                    continue
                except OSError as e:
                    saw_failure = True
                    self._note_request_failure(rep)
                    if not self.budget.withdraw():
                        self.metrics.count_budget_exhausted()
                        span.set_attr("status", "retry_budget_exhausted")
                        fail_out(f"retry budget exhausted ({e})")
                        return
                    self.metrics.count_failover(
                        "mid_stream" if emitted else "dispatch")
                    continue
                self.metrics.count_routed(rep.name, reason)
                if t_loss is not None:
                    self.metrics.set_recovery_ms(
                        (time.monotonic() - t_loss) * 1e3)
                    t_loss = None
                with resp:
                    outcome = self._relay_journal(handler, resp, emitted,
                                                  state, t0)
            finally:
                with self._lock:
                    rep.inflight -= 1
                self.metrics.add_inflight(-1)
            if outcome == "done":
                span.set_attr("tokens", len(emitted))
                self._note_request_success(rep)
                self.budget.deposit()
                self._observe_latency(time.monotonic() - t0)
                self.metrics.count_outcome(ok=True)
                return
            if outcome == "done_error":
                # the replica reported an in-band engine error (deadline,
                # cancel) — relayed as-is; not a fleet transport failure
                span.set_attr("status", "upstream_error")
                self.metrics.count_outcome(ok=False)
                return
            if outcome == "client_gone":
                span.set_attr("status", "client_gone")
                return
            # upstream_lost: the replica died mid-stream.  Evict it NOW
            # (hard evidence beats probe cadence), then resume on a
            # survivor if the retry budget allows.
            t_loss = time.monotonic()
            saw_failure = True
            self._note_request_failure(rep)
            self._evict(rep, "connection severed mid-stream")
            if key is not None:
                with self._lock:
                    # its prefix pages died with it: drop the binding
                    if self._affinity.get(key) == \
                            self.replicas.index(rep):
                        self._affinity.pop(key, None)
            if not self.budget.withdraw():
                self.metrics.count_budget_exhausted()
                span.set_attr("status", "retry_budget_exhausted")
                fail_out("retry budget exhausted mid-stream")
                return
            self.metrics.count_failover("mid_stream")
            logger.warning("stream failover: %d tokens relayed, "
                           "re-admitting on a survivor", len(emitted))

    def _write_event(self, handler, obj) -> bool:
        """One SSE event onto the (chunked) client connection; sends the
        response headers first if this is the stream's first event.
        False = the client went away."""
        try:
            if not getattr(handler, "_sse_started", False):
                handler.send_response(200)
                handler.send_header("Content-Type", "text/event-stream")
                handler.send_header("Cache-Control", "no-cache")
                handler.send_header("Transfer-Encoding", "chunked")
                handler.send_header("Connection", "close")
                handler.end_headers()
                handler.close_connection = True
                handler._sse_started = True
            data = b"data: " + json.dumps(obj).encode() + b"\n\n"
            handler.wfile.write(b"%X\r\n" % len(data) + data + b"\r\n")
            handler.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError):
            return False

    def _end_chunks(self, handler):
        try:
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _relay_journal(self, handler, resp, emitted, state, t0):
        """Parse-and-relay the upstream SSE stream.  Token events are
        journaled into `emitted` AND re-framed to the client; the final
        done event is rewritten so the client sees the TOTAL token count
        and latency across failovers.  Returns one of:
        done | done_error | upstream_lost | client_gone."""
        try:
            for line in resp:
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                try:
                    obj = json.loads(line[5:].strip())
                except ValueError:
                    continue
                if obj.get("done"):
                    out = dict(obj)
                    out["tokens"] = len(emitted)
                    out["latency_ms"] = round(
                        (time.monotonic() - t0) * 1e3, 3)
                    if not self._write_event(handler, out):
                        return "client_gone"
                    state["headers_sent"] = True
                    self._end_chunks(handler)
                    return "done_error" if obj.get("error") else "done"
                tok = obj.get("token")
                if tok is None:
                    continue
                emitted.append(tok)
                if not self._write_event(handler, {"token": tok}):
                    return "client_gone"
                state["headers_sent"] = True
        except (OSError, http.client.HTTPException):
            return "upstream_lost"
        # EOF without a done event: the replica died between events
        return "upstream_lost"

    # -- metrics federation ------------------------------------------------
    def federated_metrics(self) -> str:
        """Router registry + every live replica's /metrics scrape, each
        replica section under a `# replica=<name> <url>` banner."""
        parts = [self.metrics.prometheus_text()]
        for rep in list(self.replicas):
            if not rep.alive:
                parts.append(f"# replica={rep.name} {rep.url} DEAD\n")
                continue
            try:
                with urllib.request.urlopen(
                        rep.url + "/metrics", timeout=2.0) as resp:
                    parts.append(f"# replica={rep.name} {rep.url}\n"
                                 + resp.read().decode())
            except OSError:
                parts.append(f"# replica={rep.name} {rep.url} SCRAPE "
                             "FAILED\n")
        return "".join(parts)


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(
        description="paddle_tpu generation fleet router (prefix-affinity "
                    "+ least-loaded + health/epoch failover over N "
                    "replicas, with journaled mid-stream resume)")
    parser.add_argument("--replicas", default="",
                        help="comma-separated replica base urls, e.g. "
                             "http://127.0.0.1:8870,http://127.0.0.1:8871 "
                             "(optional with --coord: replicas are "
                             "discovered from the coordinator KV)")
    parser.add_argument("--coord", default=None,
                        help="fleet coordinator host:port (the serving "
                             "supervisor's PodCoordinator); enables "
                             "epoch-delta eviction + respawn re-admission")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0,
                        help="0 picks a free port (printed on stdout)")
    parser.add_argument("--page-size", type=int, default=None,
                        help="replica KV page size (prefix hash "
                             "alignment; must match the replicas)")
    parser.add_argument("--probe-interval", type=float, default=None)
    parser.add_argument("--dead-after", type=int, default=None)
    parser.add_argument("--hedge-floor-ms", type=float, default=None,
                        help="hedge non-streaming dispatches after "
                             "max(this, observed p99) ms; unset/0 "
                             "disables")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    urls = [u.strip() for u in args.replicas.split(",") if u.strip()]
    if not urls and not args.coord:
        parser.error("need --replicas and/or --coord")
    router = FleetRouter(urls, host=args.host, port=args.port,
                         page_size=args.page_size,
                         probe_interval_s=args.probe_interval,
                         dead_after=args.dead_after,
                         coord=args.coord,
                         hedge_floor_ms=args.hedge_floor_ms).start()
    # parse-friendly readiness line (tools/serve_smoke.sh greps it)
    print(f"paddle_tpu.serving.router listening on {router.url}",
          flush=True)
    return router.wait()


if __name__ == "__main__":
    import sys

    sys.exit(main())
