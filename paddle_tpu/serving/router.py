"""Prefix-cache-aware fleet router over N GenerationEngine replicas.

One stdlib HTTP endpoint in front of N `ServingServer` replicas, each
running its own `GenerationEngine` (own page pool, own prefix cache).
The reference scaled serving by handing every process its own
AnalysisPredictor behind an external L4 balancer (SURVEY §4c) — blind
round-robin, so two requests sharing a 4k-token system prompt land on
different predictors and BOTH pay the full prefill.  This router makes
the placement decision cache-topology-aware:

  prefix_hit       the prompt's page-aligned prefix (the exact region
                   `prefix_cache.shareable_pages` would share) hashes to
                   an affinity entry — route to the replica whose prefix
                   cache already owns those KV pages, so the replica-side
                   lookup hits and prefill skips the shared pages
  least_loaded     no affinity yet — route to the replica with the
                   fewest router-side inflight requests, then remember
                   the prefix → replica binding for the next caller
  health_failover  the affinity replica is dead (>= `dead_after`
                   consecutive /healthz probe failures) — re-route to
                   the least-loaded live replica and REBIND the prefix
                   (its pages are gone with the replica; stickiness to a
                   corpse would re-miss forever)

Backpressure is not death: a replica answering 429 (generation queue
full) is healthy-but-loaded.  The router counts it
(`paddle_router_backpressure_total{replica}`), retries the request on
the remaining live replicas, and does NOT touch the health-probe
failure count — a replica must never flap out of the fleet just for
being busy (the flap would dump its whole prefix-cache working set).

Tracing: the incoming W3C `traceparent` (or a fresh head-sampled root)
becomes a `router.generate` child span whose context is forwarded to
the replica, so `/debug/spans?trace_id=` shows client → router →
replica server.generate → gen.prefill/gen.decode as ONE trace across
the hop.

`/metrics` federation: the router serves its own `RouterMetrics`
registry (co-exposable in-process via
`MonitorServer(extra_registries=[router.metrics.registry])`) followed by
every live replica's scrape under a `# replica=<name> <url>` banner —
one curl shows fleet routing counters AND per-replica genserve gauges.

Shutdown mirrors the server's latch-drain contract: SIGTERM stops new
admissions (healthz flips to draining), inflight proxied requests
finish, then the listener closes and "router drain clean" is logged
(tools/serve_smoke.sh greps it, then SIGTERMs the replicas).
"""
from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..distributed.resilience import PreemptionGuard
from ..framework import flags as _flags
from ..monitor import tracing as _tracing
from .metrics import RouterMetrics

logger = logging.getLogger("paddle_tpu.serving.router")

__all__ = ["FleetRouter", "Replica"]


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 128


class Replica:
    """Router-side view of one generation server: health-probe state +
    inflight accounting.  All mutation happens under the router lock."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.inflight = 0
        self.fails = 0          # consecutive /healthz probe failures
        self.alive = True       # optimistic until probes say otherwise
        self.draining = False

    def snapshot(self) -> dict:
        return {"name": self.name, "url": self.url,
                "alive": self.alive, "draining": self.draining,
                "inflight": self.inflight, "probe_fails": self.fails}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _send(self, code: int, body: bytes, ctype="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if code in (429, 503):
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj):
        self._send(code, json.dumps(obj).encode())

    def do_GET(self):  # noqa: N802 - http.server API
        router = self.server.owner
        if self.path == "/healthz":
            body = {"status": "draining" if router.draining else "ok",
                    "replicas": [r.snapshot() for r in router.replicas],
                    "uptime_s": router.uptime_s}
            self._send_json(503 if router.draining else 200, body)
        elif self.path == "/metrics":
            self._send(200, router.federated_metrics().encode(),
                       ctype="text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802 - http.server API
        router = self.server.owner
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n)
        if self.path != "/generate":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        if router.draining:
            self._send_json(503, {"error": "router draining"})
            return
        tracer = _tracing.default_tracer()
        span = tracer.start_span("router.generate",
                                 traceparent=self.headers.get("traceparent"))
        try:
            router._route_generate(self, raw, span)
        finally:
            span.end()

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("%s - %s", self.address_string(), fmt % args)


class FleetRouter:
    """N generation replicas behind one endpoint with prefix-affinity,
    least-loaded fallback, health failover, and SSE pass-through."""

    def __init__(self, replica_urls, host="127.0.0.1", port=0, *,
                 page_size=None, probe_interval_s=None, dead_after=None,
                 request_timeout_s=120.0, install_signal_handlers=True,
                 drain_timeout_s=30.0):
        if not replica_urls:
            raise ValueError("FleetRouter needs at least one replica url")
        self.replicas = [Replica(f"r{i}", u)
                         for i, u in enumerate(replica_urls)]
        self.page_size = int(
            page_size or _flags.flag("FLAGS_genserve_page_size", 16))
        self.probe_interval_s = float(
            probe_interval_s
            or _flags.flag("FLAGS_router_probe_interval_s", 0.5))
        self.dead_after = int(
            dead_after or _flags.flag("FLAGS_router_dead_after", 3))
        self.request_timeout_s = float(request_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._install_signals = install_signal_handlers
        self._host = host
        self._requested_port = int(port)
        self.metrics = RouterMetrics()
        self._lock = threading.RLock()
        self._affinity: dict[str, int] = {}   # prefix hash -> replica idx
        self._httpd = None
        self._guard = None
        self._threads = []
        self._done = threading.Event()
        self._stop_probe = threading.Event()
        self._drain_clean = None
        self._shutdown_once = threading.Lock()
        self._started_at = None
        self.draining = False

    # -- lifecycle ---------------------------------------------------------
    @property
    def uptime_s(self) -> float:
        return round(time.monotonic() - self._started_at, 1) \
            if self._started_at is not None else 0.0

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd \
            else self._requested_port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self) -> "FleetRouter":
        self._probe_all()  # synchronous first pass: route correctly from
        self._httpd = _HTTPServer((self._host, self._requested_port),
                                  _Handler)  # request #1, not probe #2
        self._httpd.owner = self
        self._started_at = time.monotonic()
        if self._install_signals:
            self._guard = PreemptionGuard()
            self._guard.__enter__()
        t_serve = threading.Thread(target=self._httpd.serve_forever,
                                   kwargs={"poll_interval": 0.05},
                                   daemon=True, name="paddle-router-http")
        t_probe = threading.Thread(target=self._probe_loop, daemon=True,
                                   name="paddle-router-probe")
        t_watch = threading.Thread(target=self._watch, daemon=True,
                                   name="paddle-router-sigwatch")
        self._threads = [t_serve, t_probe, t_watch]
        for t in self._threads:
            t.start()
        logger.info("router on %s over %d replicas (%s)", self.url,
                    len(self.replicas),
                    ", ".join(r.url for r in self.replicas))
        return self

    def _watch(self):
        while not self._done.wait(0.05):
            if self._guard is not None and self._guard.preempted:
                logger.warning("signal %s latched — draining router",
                               self._guard.signum)
                self.shutdown()
                return

    def shutdown(self) -> bool:
        """Drain: reject new admissions, let inflight proxied requests
        finish, close the listener.  Idempotent; True = clean."""
        with self._shutdown_once:
            if self._drain_clean is not None:
                return self._drain_clean
            self.draining = True
            self._stop_probe.set()
            deadline = time.monotonic() + self.drain_timeout_s
            while time.monotonic() < deadline:
                with self._lock:
                    if all(r.inflight == 0 for r in self.replicas):
                        break
                time.sleep(0.02)
            with self._lock:
                clean = all(r.inflight == 0 for r in self.replicas)
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()
            if self._guard is not None:
                self._guard.__exit__(None, None, None)
                self._guard = None
            self._drain_clean = clean
            self._done.set()
            logger.info("router drain %s", "clean" if clean else "TIMED OUT")
            return clean

    def wait(self, timeout=None) -> int:
        if not self._done.wait(timeout):
            return -1
        for t in self._threads:
            t.join(5.0)
        return 0 if self._drain_clean else 1

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- health probing ----------------------------------------------------
    def _probe_one(self, rep: Replica):
        try:
            req = urllib.request.Request(rep.url + "/healthz")
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                ok = resp.status == 200
                rep.draining = False
        except urllib.error.HTTPError as e:
            # 503 healthz = replica draining: stop routing to it, but it
            # is answering — not a crash
            ok = False
            rep.draining = (e.code == 503)
        except OSError:
            ok = False
            rep.draining = False
        with self._lock:
            if ok:
                rep.fails = 0
                rep.alive = True
            else:
                rep.fails += 1
                if rep.fails >= self.dead_after or rep.draining:
                    rep.alive = False

    def _probe_all(self):
        for rep in self.replicas:
            self._probe_one(rep)
        with self._lock:
            self.metrics.set_healthy(
                sum(1 for r in self.replicas if r.alive))

    def _probe_loop(self):
        while not self._stop_probe.wait(self.probe_interval_s):
            self._probe_all()

    # -- routing policy ----------------------------------------------------
    def _prefix_key(self, prompt) -> str | None:
        """Hash of the page-aligned shareable prefix — EXACTLY the
        region the replica's PrefixCache would share
        (`shareable_pages`: the last page is never shared because the
        next generated token writes into it)."""
        n_pages = max(0, (len(prompt) - 1) // self.page_size)
        if n_pages == 0:
            return None
        head = prompt[:n_pages * self.page_size]
        return hashlib.sha1(
            b",".join(b"%d" % int(t) for t in head)).hexdigest()

    def _pick(self, key, exclude=()):
        """(replica, reason) under the routing policy; None when no live
        replica remains.  `exclude`: replicas already tried this request
        (429 backpressure retries)."""
        with self._lock:
            live = [r for r in self.replicas
                    if r.alive and r.name not in exclude]
            if not live:
                return None, None
            if key is not None:
                idx = self._affinity.get(key)
                if idx is not None:
                    aff = self.replicas[idx]
                    if aff.alive and aff.name not in exclude:
                        return aff, "prefix_hit"
                    # affinity points at a dead/busy replica: rebind
                    reason = "health_failover" if not aff.alive \
                        else "least_loaded"
                else:
                    reason = "least_loaded"
            else:
                reason = "least_loaded"
            rep = min(live, key=lambda r: (r.inflight, r.name))
            if key is not None:
                self._affinity[key] = self.replicas.index(rep)
            return rep, reason

    # -- proxying ----------------------------------------------------------
    def _route_generate(self, handler, raw, span):
        try:
            payload = json.loads(raw or b"{}")
            prompt = payload.get("prompt") or []
            stream = bool(payload.get("stream", False))
        except ValueError:
            handler._send_json(400, {"error": "bad request: invalid JSON"})
            return
        key = self._prefix_key(prompt)
        tried: set[str] = set()
        while True:
            rep, reason = self._pick(key, exclude=tried)
            if rep is None:
                if tried:   # every live replica answered 429
                    span.set_attr("status", "backpressure_exhausted")
                    handler._send_json(
                        429, {"error": "all replicas at capacity"})
                else:
                    span.set_attr("status", "no_live_replica")
                    handler._send_json(
                        503, {"error": "no live replica"})
                return
            tried.add(rep.name)
            status = self._proxy_once(handler, rep, reason, raw, stream,
                                      span)
            if status == 429:
                # backpressure: count it, try the next live replica —
                # and DO NOT touch rep.fails (a busy replica is healthy)
                self.metrics.count_backpressure(rep.name)
                continue
            return

    def _proxy_once(self, handler, rep, reason, raw, stream, span):
        """Forward one request to `rep`.  Returns the upstream HTTP
        status (429 lets the caller retry elsewhere; anything else has
        already been relayed to the client)."""
        span.set_attr("replica", rep.name)
        span.set_attr("reason", reason)
        headers = {"Content-Type": "application/json",
                   "traceparent": span.traceparent}
        req = urllib.request.Request(rep.url + "/generate", data=raw,
                                     headers=headers, method="POST")
        with self._lock:
            rep.inflight += 1
        self.metrics.add_inflight(1)
        try:
            try:
                resp = urllib.request.urlopen(
                    req, timeout=self.request_timeout_s)
            except urllib.error.HTTPError as e:
                body = e.read()
                if e.code == 429:
                    return 429
                handler._send(e.code, body,
                              e.headers.get("Content-Type",
                                            "application/json"))
                return e.code
            except OSError as e:
                # transport failure mid-request: surface as 502; the
                # probe loop decides whether the replica is dead
                handler._send_json(
                    502, {"error": f"replica {rep.name} unreachable: {e}"})
                return 502
            self.metrics.count_routed(rep.name, reason)
            with resp:
                if stream and resp.status == 200:
                    self._relay_sse(handler, resp)
                else:
                    body = resp.read()
                    handler._send(resp.status, body,
                                  resp.headers.get("Content-Type",
                                                   "application/json"))
            return resp.status
        finally:
            with self._lock:
                rep.inflight -= 1
            self.metrics.add_inflight(-1)

    def _relay_sse(self, handler, resp):
        """Re-frame the replica's SSE stream onto the client connection
        as it arrives (urllib undoes the upstream chunked framing; we
        re-chunk) — the router adds no buffering to inter-token
        latency."""
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.send_header("Connection", "close")
        handler.end_headers()
        handler.close_connection = True
        try:
            for line in resp:
                if not line.strip():
                    continue
                data = line if line.endswith(b"\n") else line + b"\n"
                data += b"\n"   # restore the SSE event separator
                handler.wfile.write(b"%X\r\n" % len(data) + data + b"\r\n")
                handler.wfile.flush()
            handler.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; upstream closes via `with resp`

    # -- metrics federation ------------------------------------------------
    def federated_metrics(self) -> str:
        """Router registry + every live replica's /metrics scrape, each
        replica section under a `# replica=<name> <url>` banner."""
        parts = [self.metrics.prometheus_text()]
        for rep in self.replicas:
            if not rep.alive:
                parts.append(f"# replica={rep.name} {rep.url} DEAD\n")
                continue
            try:
                with urllib.request.urlopen(
                        rep.url + "/metrics", timeout=2.0) as resp:
                    parts.append(f"# replica={rep.name} {rep.url}\n"
                                 + resp.read().decode())
            except OSError:
                parts.append(f"# replica={rep.name} {rep.url} SCRAPE "
                             "FAILED\n")
        return "".join(parts)


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(
        description="paddle_tpu generation fleet router (prefix-affinity "
                    "+ least-loaded + health failover over N replicas)")
    parser.add_argument("--replicas", required=True,
                        help="comma-separated replica base urls, e.g. "
                             "http://127.0.0.1:8870,http://127.0.0.1:8871")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0,
                        help="0 picks a free port (printed on stdout)")
    parser.add_argument("--page-size", type=int, default=None,
                        help="replica KV page size (prefix hash "
                             "alignment; must match the replicas)")
    parser.add_argument("--probe-interval", type=float, default=None)
    parser.add_argument("--dead-after", type=int, default=None)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    urls = [u.strip() for u in args.replicas.split(",") if u.strip()]
    router = FleetRouter(urls, host=args.host, port=args.port,
                         page_size=args.page_size,
                         probe_interval_s=args.probe_interval,
                         dead_after=args.dead_after).start()
    # parse-friendly readiness line (tools/serve_smoke.sh greps it)
    print(f"paddle_tpu.serving.router listening on {router.url}",
          flush=True)
    return router.wait()


if __name__ == "__main__":
    import sys

    sys.exit(main())
