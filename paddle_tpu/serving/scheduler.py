"""Iteration-level slot scheduler for continuous-batching generation.

Host-side bookkeeping only (the Orca-style scheduling half of the
generation engine): which decode lane holds which request, which lanes
are free, and which occupied lanes must be swept (client cancellation,
deadline expiry).  All device state lives in serving/kv_cache.py; the
scheduler never touches a jax array, so it needs no lock beyond the
engine's single decode thread owning it.
"""
from __future__ import annotations

import time

__all__ = ["SlotScheduler"]


class SlotScheduler:
    """Fixed-capacity slot table: ``admit`` at iteration boundaries,
    ``retire`` on EOS/length, ``sweep`` for mid-decode preemption."""

    def __init__(self, max_slots: int):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = int(max_slots)
        # LIFO free list: hot slots are reused first, which keeps the
        # occupied lanes dense at low load (cache locality on TPU)
        self._free = list(range(self.max_slots - 1, -1, -1))
        self._occupants: dict[int, object] = {}   # slot -> request

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def occupied(self) -> dict:
        return self._occupants

    def has_free(self) -> bool:
        return bool(self._free)

    def admit(self, request) -> int:
        """Claim a free slot for ``request``; raises when full (the
        engine checks ``has_free()`` first — a raise is a logic bug)."""
        slot = self._free.pop()
        self._occupants[slot] = request
        return slot

    def retire(self, slot: int):
        """Release ``slot`` back to the free list; returns its request."""
        req = self._occupants.pop(slot)
        self._free.append(slot)
        return req

    def sweep(self, now=None):
        """Occupied lanes whose request is cancelled or past deadline:
        [(slot, request, reason)].  The engine releases them on-device
        and retires them here."""
        now = time.monotonic() if now is None else now
        out = []
        for slot, req in self._occupants.items():
            if getattr(req, "cancelled", False):
                out.append((slot, req, "cancelled"))
            elif getattr(req, "deadline", None) is not None \
                    and now > req.deadline:
                out.append((slot, req, "deadline_expired"))
        return out
