"""Iteration-level slot + page scheduler for continuous-batching
generation.

Host-side bookkeeping only (the Orca-style scheduling half of the
generation engine): which decode lane holds which request, which lanes
are free, which occupied lanes must be swept (client cancellation,
deadline expiry) — and, since the paged KV cache, whether the PAGE POOL
can absorb a request's worst case.  Admission reserves
``ceil((prompt + max_new) / page_size)`` pages minus any shared prefix
pages; a free slot with an exhausted pool queues the request instead of
admitting it into an in-graph free-list underflow.  The invariant the
reservation buys: the device's ``free_count`` register never drops
below ``pages_available`` here, so decode's in-graph tail-page
allocation cannot underflow.

All device state lives in serving/kv_cache.py; the scheduler never
touches a jax array, so it needs no lock beyond the engine's single
decode thread owning it.
"""
from __future__ import annotations

import time

__all__ = ["SlotScheduler"]


class SlotScheduler:
    """Fixed-capacity slot + page table: ``admit`` at iteration
    boundaries, ``retire`` on EOS/length, ``sweep`` for mid-decode
    preemption."""

    def __init__(self, max_slots: int, num_pages: int | None = None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = int(max_slots)
        # LIFO free list: hot slots are reused first, which keeps the
        # occupied lanes dense at low load (cache locality on TPU)
        self._free = list(range(self.max_slots - 1, -1, -1))
        self._occupants: dict[int, object] = {}   # slot -> request
        self.num_pages = None if num_pages is None else int(num_pages)
        self._reserved: dict[int, int] = {}       # slot -> pages reserved
        self._shared_resident = 0                 # prefix-cache pages

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def occupied(self) -> dict:
        return self._occupants

    def has_free(self) -> bool:
        return bool(self._free)

    # -- page accounting ---------------------------------------------------
    @property
    def pages_reserved(self) -> int:
        return sum(self._reserved.values())

    @property
    def pages_available(self) -> int:
        """Pages the pool can still promise to a new admission: total
        minus active worst-case reservations minus prefix-cache
        residents (conservative — a slot's own registered pages may be
        counted in both, never under)."""
        if self.num_pages is None:
            return 1 << 30
        return self.num_pages - self.pages_reserved - self._shared_resident

    def set_shared_resident(self, n_pages: int):
        """Pages currently held by the prefix cache (refcount > 0) —
        the engine refreshes this after register/unpin/evict."""
        self._shared_resident = int(n_pages)

    def can_admit(self, n_pages: int) -> bool:
        """True when a free slot exists AND the pool can reserve the
        request's worst-case ``n_pages`` — an exhausted pool queues the
        request even with lanes free (admit-and-crash is the failure
        mode this check exists to prevent)."""
        return bool(self._free) and n_pages <= self.pages_available

    def admit(self, request, n_pages: int = 0) -> int:
        """Claim a free slot for ``request`` and reserve its worst-case
        page demand; raises when full (the engine checks ``can_admit``
        first — a raise is a logic bug)."""
        slot = self._free.pop()
        self._occupants[slot] = request
        self._reserved[slot] = int(n_pages)
        return slot

    def retire(self, slot: int):
        """Release ``slot`` (and its page reservation) back to the free
        lists; returns its request."""
        req = self._occupants.pop(slot)
        self._free.append(slot)
        self._reserved.pop(slot, None)
        return req

    def prefilling(self) -> int:
        """Occupied lanes still mid-chunked-prefill (request carries a
        truthy ``prefilling``) — they hold a slot + full worst-case page
        reservation but are not yet armed for decode."""
        return sum(1 for req in self._occupants.values()
                   if getattr(req, "prefilling", False))

    def sweep(self, now=None):
        """Occupied lanes whose request is cancelled or past deadline:
        [(slot, request, reason)].  The engine releases them on-device
        and retires them here.

        Mid-chunk prefills are swept EXACTLY like armed decode lanes:
        a chunked prompt's already-written pages are private table
        entries above the lane's ``pinned`` register (the shared-prefix
        head), so the engine's release executable returns every one of
        them to the free stack the moment the sweep fires — a cancelled
        32k-token prefill must not strand half its pages until some
        later decode notices.  tests/test_spec_decode.py pins this with
        a pool-occupancy tripwire (cancel mid-chunk, assert free_count
        returns to baseline)."""
        now = time.monotonic() if now is None else now
        out = []
        for slot, req in self._occupants.items():
            if getattr(req, "cancelled", False):
                out.append((slot, req, "cancelled"))
            elif getattr(req, "deadline", None) is not None \
                    and now > req.deadline:
                out.append((slot, req, "deadline_expired"))
        return out
